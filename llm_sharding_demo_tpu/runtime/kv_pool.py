"""Paged KV-cache memory subsystem: block pool, CoW sharing, admission.

After PRs 1-3 the binding serving constraint is KV memory, not
scheduling: every decode row owns a contiguous ``max_seq`` cache for its
whole lifetime, the prefix store duplicated entire prefill states per
entry, and nothing sheds load under pressure — the reference, of course,
has no KV state at all (it re-forwards the full sequence per token,
reference server.py:169-181). This module is the first-class manager:

- ``BlockAllocator`` — host-side, device-free accounting: ref-counted
  blocks, a content-keyed prefix registry whose entries share blocks
  structurally (entry for chunks [0, m) references the same physical
  blocks as the deeper entry for [0, m+k) — the duplication the old
  store paid is gone), LRU eviction of zero-ref prefix blocks, and
  watermark admission (``can_admit`` holds back a growth reserve so
  live batches can deepen without instantly preempting).
- ``KVBlockPool`` — the device pool (one
  ``[L, num_blocks+1, 2, Hkv, block_size, hd]`` buffer; per layer the
  ``[num_blocks, 2, n_kv_head, block_size, head_dim]`` block array,
  plus the shared trash block) + the jitted gather/scatter/copy
  programs over it (``ops.paged_attention``) and the pool-derived
  ``kv_cache_blocks_*`` gauges.
- ``PagedKVRunner`` — solo/batched paged decode over an unmodified
  ``DecodeEngine``: prefill with THE engine's program, scatter the
  state into blocks, then per decode segment gather -> run the
  engine's OWN ``_decode_seg`` -> scatter back. The compiled model
  programs are untouched and shared with contiguous serving, so paged
  decode is byte-equal by construction (greedy and seeded sample,
  pinned). With a pool-backed ``PrefixCachingEngine`` attached, a
  prefix hit REFERENCES the store's blocks in the row's table instead
  of copying the prefill state — live decode and the prefix store
  share one physical copy, with the partially-filled frontier block
  copy-on-write'd before the row's first write into it.

Quantized block storage (``block_dtype="int8"`` / ``"fp8"``, the
serving ``KV_POOL_DTYPE`` knob): the pool stores narrow codes plus one
f32 absmax scale per (layer, block, k|v, kv-head) — ``ops.kv_quant`` —
with quantize-on-scatter / dequant-on-gather movers (``_gather_q`` /
``_scatter_q`` / ``_scatter_row_q`` / ``_copy_q``, the ``_q`` jit
family). At int8 that is ~4x the f32 pool's rows-per-byte at equal HBM:
the allocator contract (refcounts, CoW, prefix sharing, GRAFTSAN
provenance) is untouched — quantization changes block CONTENTS only —
while capacity-per-byte scales with the narrow dtype. The path is
``exact: False`` under the ``kv.int8``/``kv.fp8`` tolerance budgets
(utils.graftnum); full-precision pools construct ONLY the plain mover
family, so every paged≡contiguous byte-equality pin is structurally
confined to them.

Preemption (the admission story's other half) lives in
``runtime.iterbatch``: under pool exhaustion the scheduler parks the
lowest-priority row, frees its blocks, and later resumes it by
RECOMPUTE — re-prefilling prompt + already-emitted tokens and
continuing the row's own per-step PRNG chain, which reproduces the
un-preempted stream byte-identically (prefix-stable key splits +
prefill/incremental KV equality, pinned by tests). ``serving.app``
turns sustained exhaustion into 429 + Retry-After instead of queueing
unboundedly.

Block lifecycle (docs/ARCHITECTURE.md has the full diagram)::

    free -> allocated (ref=1, private)
         -> shared    (ref>1: live table refs and/or prefix entries)
         -> evictable (ref held only by prefix entries, LRU-ordered)
         -> free      (last ref dropped / entry evicted)

Writers never mutate a shared block: extension into a shared frontier
block goes through ``cow_copy`` (allocate, copy, retarget the table
entry, deref the original).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import kv_quant as KVQ
from ..ops import paged_attention as PA
from ..ops.attention import KVCache
from ..utils import graftfault, graftmem, graftsched, graftscope, \
    grafttime, tracing
from ..utils.metrics import DEFAULT_KV_BLOCK_SIZE, REGISTRY, CompileWatch
from .engine import (DecodeEngine, GenerateResult, SamplingConfig,
                     _eos_capped_segments, _split_keys, _step_keys,
                     prepare_generate, select_token)

# Static-analysis contract (tools/graftcheck): every ``jax.jit`` site in
# this module, by holding attribute — enumerated by the recompile-budget
# certifier; an undeclared site is a lint finding. ``_poison`` is the
# sanitizer's free-block poisoner (GRAFTSAN=1 only — see GraftsanError).
# The ``_q`` names are the quantized-pool mover family (constructed
# instead of — never alongside — the plain family when ``block_dtype``
# is set); ``_poison_q`` is its GRAFTSAN-only poisoner.
JIT_ENTRY_POINTS = ("_gather", "_scatter", "_scatter_row", "_copy",
                    "_poison", "_gather_q", "_scatter_q",
                    "_scatter_row_q", "_copy_q", "_poison_q")

# Observability contract (tools/graftcheck scope pass + utils/graftscope):
# every serving-path mover's dispatch is timed into the graftscope ring,
# keyed (batch, table width) — the certifier's paged_runner_keys model.
# ``_poison``/``_poison_q`` are deliberately NOT profiled: they are the
# GRAFTSAN-only free-block poisoners, sanitizer hooks off every serving
# path — baselined in tools/graftcheck/baseline.txt with that
# justification.
PROFILED_SCOPES = ("_gather", "_scatter", "_scatter_row", "_copy",
                   "_gather_q", "_scatter_q", "_scatter_row_q",
                   "_copy_q")

# Timeline contract (tools/graftcheck timeline pass): the allocator's
# LRU evictions land on the unified causal stream (utils/grafttime) —
# an eviction storm is only diagnosable when it sits on the same clock
# as the admissions/preemptions that provoked it. (Admission events are
# the SCHEDULERS' story — iterbatch emits them with the rid; the
# allocator's view is the block economy.)
TIMELINE_EVENTS = {
    "eviction": "BlockAllocator._evict_lru_locked",
}

# HBM-ledger contract (tools/graftcheck memory pass + utils/graftmem):
# the pool's two long-lived device planes, by graftmem component. The
# block-storage plane holds full-precision blocks OR quantized codes
# (one buffer either way — ``pool_codes`` names the plane, the
# ``block_dtype`` stats field names what a block IS); the f32 scales
# plane exists only for quantized pools. Sizes are CONSTANT across the
# donated movers (every rebind is shape-identical), so registration at
# construction is the whole lifecycle — /healthz derives ``pool_bytes``
# from these entries, never from shape arithmetic.
MEMORY_LEDGER = {
    "data": "pool_codes",
    "scales": "pool_scales",
}

# Placement contract (tools/graftcheck placement pass + utils/
# graftshard): the pool's two device planes are EXPLICITLY replicated
# today — the single-device paged engine owns the whole block table.
# ``kvp`` is the declared partition axis a mesh-sharded pool will
# split the kv-head dim over (ROADMAP item 1; the planner already
# enumerates and prices kvp candidates against this vocabulary) — the
# builder that lands it flips these holdings to "kvp" and the dynamic
# auditor (GRAFTSHARD=1) starts requiring that placement on the live
# buffers at track()/update() time.
PLACEMENT_CONTRACT = {
    "mesh_axes": ("kvp",),
    "holding:data": "replicated",
    "holding:scales": "replicated",
}


# graftscope program-key derivations (the certifier's model: gather/
# scatter key by (batch, table width) — block ids and placement are
# traced operands and never key programs)

def _gather_scope_key(pool, tables):
    return (int(tables.shape[0]), int(tables.shape[1]))


def _scatter_scope_key(pool, k, v, tables):
    return (int(tables.shape[0]), int(tables.shape[1]))


def _scatter_row_scope_key(pool, k, v, table_row, roll):
    return (int(k.shape[-2]), int(table_row.shape[0]))


def _copy_scope_key(pool, src, dst):
    return (int(src.shape[0]),)


# quantized-family keys: same (batch, table width) model — the scale
# array rides along as a second carried operand and never keys programs
# beyond the shapes the data already keys

def _gather_q_scope_key(data, scales, tables):
    return (int(tables.shape[0]), int(tables.shape[1]))


def _scatter_q_scope_key(data, scales, k, v, tables):
    return (int(tables.shape[0]), int(tables.shape[1]))


def _scatter_row_q_scope_key(data, scales, k, v, table_row, roll):
    return (int(k.shape[-2]), int(table_row.shape[0]))


def _copy_q_scope_key(data, scales, src, dst):
    return (int(src.shape[0]),)

# Donation contract (tools/graftcheck sanitize pass): the pool movers
# all consume the pool buffer itself (arg 0) — ``self.data`` is re-bound
# from every call's output under ``_dev_lock``, and nothing may hold a
# host view of it. The quantized movers additionally consume the scale
# array (arg 1): ``self.scales`` is re-bound in the same statement, so
# (data, scales) stay one atomic device state.
DONATED_ARGS = {"_scatter": (0,), "_scatter_row": (0,), "_copy": (0,),
                "_poison": (0,), "_scatter_q": (0, 1),
                "_scatter_row_q": (0, 1), "_copy_q": (0, 1),
                "_poison_q": (0, 1)}

# Pool-mover lease scopes (tools/graftcheck sanitize pass): the paged
# runner's two mover sites — every block id they move is a live
# allocation of this generate (owned/shared row ids) or the trash block.
POOL_MOVER_SCOPES = ("PagedKVRunner._prefill_tables",
                     "PagedKVRunner._decode")

# Tier-movement contract (tools/graftcheck tier pass): the ONLY scope
# here allowed to invoke tier movement is the pressure hook wired by
# attach_tier — the allocator calls it OUTSIDE ``_lock``, and every
# other demotion/promotion site lives in kv_tier/prefix_cache behind
# their own SPILL_SCOPES declarations.
SPILL_SCOPES = ("KVBlockPool.attach_tier",)

# Lock-discipline contract (tools/graftcheck locks pass): every shared
# mutable attribute, by guarding lock. The allocator's accounting
# (free list, refcounts, prefix registry, sanitizer provenance,
# counters) lives under its reentrant ``_lock``; the device pool buffer
# is rebound only under ``_dev_lock``. ``*_locked``-suffix helpers run
# with the caller's hold by convention.
GUARDED_STATE = {
    "_free": "_lock", "_ref": "_lock", "_prefix": "_lock",
    "_prefix_ref": "_lock", "_san_*": "_lock",
    "evictions": "_lock", "cow_copies": "_lock",
    "data": "_dev_lock", "scales": "_dev_lock",
}

# Numerics contract (tools/graftcheck numerics pass): the quantized
# mover family is ``exact: False`` — it routes to the seeded ``kv.*``
# tolerance budgets in utils/graftnum.py TOLERANCE_POLICY. The entries
# name the per-instance nested impls (the lint resolver indexes nested
# defs by qualname suffix). All four are ``carried``: the narrowing/
# widening casts live in ops.kv_quant's own contracted quantizers —
# these impls carry (data, scales) through and pick the regime's
# quantizer at construction. ``kv.int8`` is the representative oracle
# path for the regime-shared programs (gather/copy compile once per
# shape for either storage dtype); the fp8-specific budget routes
# through ops.kv_quant's ``scatter_kv_fp8``/``quantize_blocks_fp8``.
PRECISION_CONTRACT = {
    "_gather_q_impl": {"regime": "carried", "exact": False,
                       "oracle": "kv.int8", "casts": ("carried",)},
    "_scatter_q_impl": {"regime": "carried", "exact": False,
                        "oracle": "kv.int8", "casts": ("carried",)},
    "_scatter_row_q_impl": {"regime": "carried", "exact": False,
                            "oracle": "kv.int8", "casts": ("carried",)},
    "_copy_q_impl": {"regime": "carried", "exact": True, "casts": ()},
}

# Permitted acquisition order: device ops validate tables against live
# allocator state, so ``_dev_lock`` may hold across an ``_lock``
# acquisition — never the reverse (``_notify_freed`` fires the poison
# hook OUTSIDE ``_lock`` precisely to keep this order acyclic).
LOCK_ORDER = ("_dev_lock", "_lock")

# Locks whose documented job is serializing DEVICE work: jit dispatch /
# device sync under them is the design (the pool buffer is donated
# through every scatter; the solo runner runs one generation at a
# time), not a blocking-under-lock finding.
DEVICE_LOCKS = ("_dev_lock", "_gen_lock")

# gauge/stats label spelling for full-precision storage, keyed by numpy
# dtype name — the quantized regimes label with their graftnum tokens
# directly, so the ``block_dtype`` label space is exactly the regime
# vocabulary
_REGIME_LABELS = {"float32": "f32", "bfloat16": "bf16",
                  "float16": "f16", "float64": "f64"}


def bytes_per_block(n_layer: int, n_kv_head: int, block_size: int,
                    head_dim: int, dtype=jnp.float32,
                    block_dtype: Optional[str] = None) -> int:
    """HBM bytes one physical block costs, scales included: the unit
    the capacity bench (`kv_quant_capacity`) uses to size an int8 and
    an f32 pool to the SAME byte budget, and the number the
    ``kv_pool_bytes_per_block`` gauge publishes. Quantized blocks pay
    ``2 * n_kv_head`` f32 scales per layer on top of the narrow codes
    (1/(block_size*head_dim) of the data — negligible, but counted)."""
    slots = n_layer * 2 * n_kv_head * block_size * head_dim
    if block_dtype is None:
        return slots * np.dtype(dtype).itemsize
    storage = KVQ.STORAGE_DTYPES[block_dtype]
    scale_bytes = n_layer * 2 * n_kv_head * np.dtype(np.float32).itemsize
    return slots * np.dtype(storage).itemsize + scale_bytes


class PoolExhausted(RuntimeError):
    """No allocation possible even after evicting every zero-ref prefix
    entry. Schedulers catch this and preempt; serving turns sustained
    exhaustion into 429."""


class GraftsanError(RuntimeError, ValueError):
    """A memory-safety invariant violation caught by the graftsan
    dynamic sanitizer (``GRAFTSAN=1``): double-free, use-after-free
    gather/scatter, CoW write to a shared block, refcount-conservation
    drift, or a leak at teardown. Messages carry the offending block id
    and the provenance (call sites) of the grants/frees involved.

    Also a ``ValueError``: the sanitizer UPGRADES the allocator's plain
    double-free ValueError with provenance, and callers (and tests)
    catching the documented ValueError contract must keep working when
    the sanitizer is armed."""


def _graftsan_enabled() -> bool:
    return os.environ.get("GRAFTSAN", "") not in ("", "0")


def _call_site(skip_file: str = __file__) -> str:
    """``file.py:line (func)`` of the nearest caller frame outside this
    module — the provenance unit the sanitizer records per grant/free."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == skip_file:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return (f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno} "
            f"({f.f_code.co_name})")


# live sanitizing allocators, for suite-level teardown sweeps
# (``graftsan_sweep`` — the conftest hook under GRAFTSAN=1)
_SAN_ALLOCATORS: "weakref.WeakSet[BlockAllocator]" = weakref.WeakSet()


def graftsan_sweep(timeout: float = 2.0) -> None:
    """Assert every live sanitizing allocator is quiesced (no leaked
    caller refs): the teardown hook the suite runs after each test
    under ``GRAFTSAN=1``. Raises ``GraftsanError`` listing each leaked
    block with its grant-site provenance."""
    for alloc in list(_SAN_ALLOCATORS):
        alloc.graftsan_assert_quiesced(timeout=timeout)


@dataclasses.dataclass(frozen=True)
class PoolStats:
    blocks_total: int
    blocks_free: int
    blocks_in_use: int      # any ref (live rows and/or prefix entries)
    blocks_evictable: int   # in_use blocks whose refs are ALL prefix refs
    prefix_entries: int
    evictions: int
    cow_copies: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class BlockAllocator:
    """Host-side ref-counted block accounting. Pure bookkeeping — no
    device arrays — so every policy (refcounts, CoW, LRU, watermarks)
    is unit-testable without a pool.

    ``watermark`` bounds ADMISSION, not allocation: ``can_admit(n)``
    refuses while ``n`` would push referenced blocks past
    ``watermark * num_blocks``, keeping the remainder free as growth
    headroom for already-admitted rows (so preemption stays the
    exception, not the steady state). ``alloc`` itself may use the
    reserve — that is what it is for.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 watermark: float = 0.9,
                 sanitize: Optional[bool] = None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks={num_blocks} must be >= 1")
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be >= 1")
        if not 0.0 < watermark <= 1.0:
            raise ValueError(f"watermark={watermark} must be in (0, 1]")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.watermark = watermark
        self._lock = graftsched.rlock("kv_pool.BlockAllocator._lock")
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref: Dict[int, int] = {}
        # content-key -> tuple(block ids); insertion order IS the LRU
        # order (lookups move_to_end). Each entry holds one ref per id,
        # tracked separately in _prefix_ref so "evictable" is decidable.
        self._prefix: "OrderedDict[bytes, Tuple[int, ...]]" = OrderedDict()
        self._prefix_ref: Dict[int, int] = {}
        self.evictions = 0
        self.cow_copies = 0
        # graftsan dynamic sanitizer (GRAFTSAN=1, or explicit flag):
        # per-block grant-site provenance, refcount-conservation asserts
        # at every boundary, freed-block poisoning (via _on_free — the
        # owning pool wires its trash-copy writer in), and leak reports
        # at teardown (graftsan_report / graftsan_assert_quiesced).
        self.sanitize = (_graftsan_enabled() if sanitize is None
                         else sanitize)
        self._san_owner: Dict[int, List[str]] = {}   # grant sites, LIFO
        self._san_freed: Dict[int, str] = {}         # last freeing site
        self._san_grants = 0
        self._san_drops = 0
        self._on_free: Optional[Callable[[List[int]], None]] = None
        # grafttier demotion hook (runtime/kv_tier.py, wired by
        # KVBlockPool.attach_tier): called OUTSIDE ``_lock`` when
        # allocation pressure would otherwise LRU-evict prefix entries;
        # returns True when it moved one entry down a tier. None means
        # no tier — plain eviction is the only relief valve.
        self._tier_demote: Optional[Callable[[], bool]] = None
        if self.sanitize:
            _SAN_ALLOCATORS.add(self)

    # -- sanitizer bookkeeping (all under self._lock) ------------------------

    def _san_grant_locked(self, b: int, site: str) -> None:
        self._san_grants += 1
        self._san_owner.setdefault(b, []).append(site)
        self._san_freed.pop(b, None)

    def _san_drop_locked(self, b: int, site: str,
                         fully_freed: bool) -> None:
        self._san_drops += 1
        owners = self._san_owner.get(b)
        if owners:
            owners.pop()
        if fully_freed:
            self._san_owner.pop(b, None)
            self._san_freed[b] = site

    def _san_check_locked(self, boundary: str) -> None:
        """Refcount conservation at a boundary: free + referenced ==
        total, grants - drops == live refs, prefix refs bounded by
        total refs. A violation is an accounting bug — raise with the
        numbers, not a silent drift."""
        free_n, ref_n = len(self._free), len(self._ref)
        if free_n + ref_n != self.num_blocks:
            raise GraftsanError(
                f"[{boundary}] block conservation broken: {free_n} free "
                f"+ {ref_n} referenced != {self.num_blocks} total")
        live = sum(self._ref.values())
        if self._san_grants - self._san_drops != live:
            raise GraftsanError(
                f"[{boundary}] refcount conservation broken: "
                f"{self._san_grants} grants - {self._san_drops} drops "
                f"!= {live} live refs")
        for b, pr in self._prefix_ref.items():
            if pr > self._ref.get(b, 0):
                raise GraftsanError(
                    f"[{boundary}] block {b} holds {pr} prefix refs but "
                    f"only {self._ref.get(b, 0)} total refs")

    def freed_provenance(self, block: int) -> Optional[str]:
        """The site that last freed ``block`` (sanitizer mode), if it is
        currently free because of an explicit free/eviction."""
        with self._lock:
            return self._san_freed.get(block)

    def graftsan_report(self) -> List[dict]:
        """Leak report: blocks whose refcount exceeds their prefix-entry
        refs once all client work has retired — every such ref was
        granted to a caller that never released it. Each row carries
        the live grant-site provenance."""
        with self._lock:
            out = []
            for b in sorted(self._ref):
                extra = self._ref[b] - self._prefix_ref.get(b, 0)
                if extra > 0:
                    out.append({
                        "block": b,
                        "leaked_refs": extra,
                        "prefix_refs": self._prefix_ref.get(b, 0),
                        "grant_sites": list(self._san_owner.get(b, [])),
                    })
            return out

    def graftsan_assert_quiesced(self, timeout: float = 2.0) -> None:
        """Poll until no caller refs remain beyond prefix entries (block
        release can trail request delivery by a scheduler beat), then
        raise ``GraftsanError`` with provenance if leaks persist."""
        deadline = time.monotonic() + timeout
        leaks = self.graftsan_report()
        while leaks and time.monotonic() < deadline:
            time.sleep(0.01)
            leaks = self.graftsan_report()
        if leaks:
            lines = "; ".join(
                f"block {r['block']}: {r['leaked_refs']} leaked ref(s), "
                f"granted at {r['grant_sites']}" for r in leaks)
            raise GraftsanError(
                f"pool teardown leak: {len(leaks)} block(s) still hold "
                f"caller refs — {lines}")
        with self._lock:
            if self.sanitize:
                self._san_check_locked("teardown")

    # -- sizing --------------------------------------------------------------

    def blocks_for(self, n_slots: int) -> int:
        return max(0, -(-n_slots // self.block_size))

    # -- allocation ----------------------------------------------------------

    def _evictable_blocks_locked(self) -> int:
        return sum(1 for b, r in self._ref.items()
                   if r > 0 and r == self._prefix_ref.get(b, 0))

    def available(self) -> int:
        """Blocks obtainable right now: free + freeable-by-eviction."""
        with self._lock:
            return len(self._free) + self._evictable_blocks_locked()

    def _can_admit_locked(self, n_blocks: int) -> bool:
        """THE admission predicate (availability + watermark), under the
        caller's ``_lock`` hold — shared by the advisory ``can_admit``
        (the serving 429 gate) and the atomic ``admit_alloc`` grant, so
        the two can never drift."""
        if n_blocks > len(self._free) + self._evictable_blocks_locked():
            return False
        live = len(self._ref) - self._evictable_blocks_locked()
        return live + n_blocks <= self.watermark * self.num_blocks

    def can_admit(self, n_blocks: int) -> bool:
        """Watermark admission: would granting ``n_blocks`` keep
        referenced blocks at or under the watermark (after evicting
        prefix entries as needed)? ADVISORY — the answer can be stale
        by the time a caller acts on it; grants go through
        ``admit_alloc``, which re-evaluates under one hold."""
        with self._lock:
            if self.sanitize:
                self._san_check_locked("admission")
            return self._can_admit_locked(n_blocks)

    def _notify_freed(self, freed: List[int]) -> None:
        """Fire the sanitizer's poison hook for fully-freed blocks —
        OUTSIDE ``self._lock`` (the pool's writer takes ``_dev_lock``,
        and gather/scatter validation reads allocator state under it;
        firing inside would invert the lock order)."""
        if freed and self._on_free is not None:
            self._on_free(freed)

    def _alloc_locked(self, n: int, site: str) -> Tuple[List[int],
                                                        List[int]]:
        """Grant ``n`` blocks at ref=1 under the caller's ``_lock``
        hold, LRU-evicting as needed -> (granted, eviction-freed)."""
        evict_freed: List[int] = []
        while len(self._free) < n and self._prefix:
            evict_freed.extend(self._evict_lru_locked())
        if len(self._free) < n:
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free and no "
                f"evictable prefix entries ({len(self._ref)} blocks "
                "referenced)")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        if self.sanitize:
            for b in out:
                self._san_grant_locked(b, site)
            self._san_check_locked("alloc")
        # eviction-freed blocks this alloc immediately re-took are
        # live again — only the remainder gets poisoned
        return out, [b for b in evict_freed if b not in self._ref]

    def _demote_pressure(self, n: int) -> None:
        """Best-effort demotion pre-pass, OUTSIDE ``_lock``: while
        satisfying ``n`` would force LRU eviction and a tier is
        attached, ask it to demote the LRU prefix entry to host RAM
        instead. The hook does device reads (``spill_blocks`` under
        ``_dev_lock``), so it cannot run under ``_lock`` — this is a
        pre-pass by construction, and ``_alloc_locked``'s plain
        eviction remains the in-lock fallback when the tier refuses
        (budget exhausted, entry too large, or a concurrent race).
        Each successful demotion removes one registry entry, so the
        loop terminates."""
        hook = self._tier_demote
        if hook is None:
            return
        while True:
            with self._lock:
                pressed = len(self._free) < n and bool(self._prefix)
            if not pressed or not hook():
                return

    def alloc(self, n: int) -> List[int]:
        """Allocate ``n`` blocks at ref=1, LRU-evicting zero-ref prefix
        entries as needed (demoting them to the attached grafttier host
        tier first, when one is wired). All-or-nothing: raises
        ``PoolExhausted`` without taking anything when ``n`` cannot be
        satisfied."""
        if n == 0:
            return []
        self._demote_pressure(n)
        with self._lock:
            site = _call_site() if self.sanitize else ""
            out, evict_freed = self._alloc_locked(n, site)
        self._notify_freed(evict_freed)
        return out

    def admit_alloc(self, n: int) -> Optional[List[int]]:
        """ATOMIC watermark admission + grant: ``can_admit`` and the
        allocation run under ONE ``_lock`` hold, so no concurrent
        allocator user can slip between the check and the grant (the
        check-then-act window the two-step form leaves open turns a
        deferrable admission into a ``PoolExhausted`` request failure —
        or, raced the other way, an over-watermark grant). Returns the
        granted ids, or None when the watermark (or availability)
        refuses — the caller defers, exactly like a ``can_admit``
        False."""
        if n == 0:
            return []
        # seeded pool-exhaustion spike (graftfault): the grant refuses
        # exactly as a genuinely full pool would — the caller's
        # deferral/preemption machinery absorbs it, deterministically
        # replayable under a pinned seed
        if graftfault.inject("kv_pool.admit_alloc", "pool_spike"):
            return None
        self._demote_pressure(n)
        evict_freed: List[int] = []
        with self._lock:
            if self.sanitize:
                self._san_check_locked("admission")
            if not self._can_admit_locked(n):
                return None
            site = _call_site() if self.sanitize else ""
            out, evict_freed = self._alloc_locked(n, site)
        self._notify_freed(evict_freed)
        return out

    def note_cow(self) -> None:
        """Count one copy-on-write block copy (under ``_lock``: pools
        are shared across front ends, and an unguarded ``+= 1`` from
        two concurrent CoW paths loses updates)."""
        with self._lock:
            self.cow_copies += 1

    def ref(self, ids) -> None:
        with self._lock:
            site = _call_site() if self.sanitize else ""
            for b in ids:
                if b not in self._ref:
                    raise ValueError(f"ref of unallocated block {b}")
                self._ref[b] += 1
                if self.sanitize:
                    self._san_grant_locked(b, site)
            if self.sanitize:
                self._san_check_locked("ref")

    def free(self, ids) -> None:
        """Drop one ref per id; zero-ref blocks return to the free
        list (idempotence is the caller's problem — double-frees raise;
        the sanitizer upgrades them to ``GraftsanError`` with the
        original freeing site's provenance)."""
        freed: List[int] = []
        with self._lock:
            site = _call_site() if self.sanitize else ""
            for b in ids:
                r = self._ref.get(b)
                if r is None:
                    if self.sanitize:
                        prior = self._san_freed.get(b)
                        raise GraftsanError(
                            f"double-free of block {b} at {site}: "
                            + (f"previously freed at {prior}" if prior
                               else "block was never allocated"))
                    raise ValueError(f"free of unallocated block {b}")
                if r == 1:
                    del self._ref[b]
                    self._free.append(b)
                    freed.append(b)
                    if self.sanitize:
                        self._san_drop_locked(b, site, fully_freed=True)
                else:
                    self._ref[b] = r - 1
                    if self.sanitize:
                        self._san_drop_locked(b, site, fully_freed=False)
            if self.sanitize:
                self._san_check_locked("free")
        self._notify_freed(freed)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    # -- prefix registry -----------------------------------------------------

    def register_prefix(self, key: bytes, ids) -> None:
        """Register ``ids`` as the cached state for content ``key``.
        The entry takes its OWN ref on every block (the caller keeps
        any refs it holds); re-registering an existing key is a no-op
        beyond an LRU touch."""
        with self._lock:
            if key in self._prefix:
                self._prefix.move_to_end(key)
                return
            ids = tuple(ids)
            site = f"prefix:{_call_site()}" if self.sanitize else ""
            for b in ids:
                if b not in self._ref:
                    raise ValueError(
                        f"register_prefix of unallocated block {b}")
                self._ref[b] += 1
                self._prefix_ref[b] = self._prefix_ref.get(b, 0) + 1
                if self.sanitize:
                    self._san_grant_locked(b, site)
            self._prefix[key] = ids
            if self.sanitize:
                self._san_check_locked("register_prefix")

    def lookup_prefix(self, key: bytes) -> Optional[Tuple[int, ...]]:
        """Hit -> the entry's block ids with one caller ref added per
        block (release with ``free``); miss -> None. Hits refresh LRU
        recency."""
        with self._lock:
            ids = self._prefix.get(key)
            if ids is None:
                return None
            self._prefix.move_to_end(key)
            site = _call_site() if self.sanitize else ""
            for b in ids:
                self._ref[b] += 1
                if self.sanitize:
                    self._san_grant_locked(b, site)
            if self.sanitize:
                self._san_check_locked("lookup_prefix")
            return ids

    def has_prefix(self, key: bytes) -> bool:
        with self._lock:
            return key in self._prefix

    def drop_prefix(self, key: bytes) -> bool:
        freed: List[int] = []
        with self._lock:
            ids = self._prefix.pop(key, None)
            if ids is None:
                return False
            freed = self._deref_prefix_locked(ids)
            if self.sanitize:
                self._san_check_locked("drop_prefix")
        self._notify_freed(freed)
        return True

    def prefix_len(self) -> int:
        with self._lock:
            return len(self._prefix)

    # -- grafttier demotion surgery (runtime/kv_tier.py) ---------------------

    def lease_lru_prefix(self) -> Optional[Tuple[bytes, Tuple[int, ...]]]:
        """Peek the LRU prefix entry and take one caller ref per block
        WITHOUT refreshing recency — the tier's demote lease. The refs
        keep the blocks alive (and their contents immutable: registry
        blocks are shared, so the CoW trap guards them) while the tier
        copies them to host OUTSIDE this lock; release with ``free``
        after ``demote_pop_prefix``. None when the registry is empty."""
        with self._lock:
            if not self._prefix:
                return None
            key = next(iter(self._prefix))
            ids = self._prefix[key]
            site = f"tier:{_call_site()}" if self.sanitize else ""
            for b in ids:
                self._ref[b] += 1
                if self.sanitize:
                    self._san_grant_locked(b, site)
            if self.sanitize:
                self._san_check_locked("tier_lease")
            return key, ids

    def demote_pop_prefix(self, key: bytes, expect_ids) -> bool:
        """Drop the registry entry for ``key`` as a DEMOTION: the tier
        captured the blocks' bytes and now owns the entry's cold copy,
        so this is a tier move, not an eviction-to-oblivion (neither
        ``evictions`` nor the eviction event fires — the tier emits
        ``tier_demote`` once the host entry is installed). Returns
        False without touching anything when the entry vanished or was
        re-registered with different blocks since the lease (the tier
        discards its stale host copy)."""
        expect = tuple(expect_ids)
        freed: List[int] = []
        with self._lock:
            if self._prefix.get(key) != expect:
                return False
            del self._prefix[key]
            freed = self._deref_prefix_locked(expect)
            if self.sanitize:
                self._san_check_locked("tier_demote")
        self._notify_freed(freed)
        return True

    def _deref_prefix_locked(self, ids) -> List[int]:
        freed: List[int] = []
        site = _call_site() if self.sanitize else ""
        for b in ids:
            self._prefix_ref[b] -= 1
            if self._prefix_ref[b] == 0:
                del self._prefix_ref[b]
            if self._ref[b] == 1:
                del self._ref[b]
                self._free.append(b)
                freed.append(b)
                if self.sanitize:
                    self._san_drop_locked(b, site, fully_freed=True)
            else:
                self._ref[b] -= 1
                if self.sanitize:
                    self._san_drop_locked(b, site, fully_freed=False)
        return freed

    def _evict_lru_locked(self) -> List[int]:
        key, ids = self._prefix.popitem(last=False)
        freed = self._deref_prefix_locked(ids)
        self.evictions += 1
        REGISTRY.inc("kv_pool_evictions_total")
        # one bounded ring append under the hold (the _sample_breaker
        # precedent): the eviction joins the causal timeline at the
        # instant the block economy changed
        grafttime.emit("eviction", blocks=len(ids), freed=len(freed),
                       prefix_entries=len(self._prefix))
        if self.sanitize:
            self._san_check_locked("eviction")
        return freed

    def evict_lru(self) -> None:
        freed: List[int] = []
        with self._lock:
            if self._prefix:
                freed = self._evict_lru_locked()
        self._notify_freed(freed)

    # -- stats ---------------------------------------------------------------

    def stats(self) -> PoolStats:
        with self._lock:
            ev = self._evictable_blocks_locked()
            return PoolStats(
                blocks_total=self.num_blocks,
                blocks_free=len(self._free),
                blocks_in_use=len(self._ref),
                blocks_evictable=ev,
                prefix_entries=len(self._prefix),
                evictions=self.evictions,
                cow_copies=self.cow_copies)


class KVBlockPool:
    """The device block pool + its allocator + its compiled programs.

    One buffer ``[L, num_blocks+1, 2, Hkv, bs, hd]`` (index
    ``num_blocks`` is the shared trash block — see
    ``ops.paged_attention``). All device mutation goes through the
    jitted programs here, serialized by ``_dev_lock`` (the pool buffer
    is donated through every scatter, and concurrent front ends — a
    solo runner, the prefix store, the iteration scheduler — may share
    one pool).
    """

    def __init__(self, n_layer: int, num_blocks: int, n_kv_head: int,
                 block_size: int, head_dim: int, max_seq: int,
                 dtype=jnp.float32, watermark: float = 0.9,
                 sanitize: Optional[bool] = None,
                 block_dtype: Optional[str] = None):
        self.nbm = PA.blocks_per_row(max_seq, block_size)
        if num_blocks < self.nbm:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold even one full "
                f"row ({self.nbm} blocks at max_seq={max_seq}, "
                f"block_size={block_size}) — nothing could ever decode "
                "to budget")
        self.block_size = block_size
        self.max_seq = max_seq
        self.trash = num_blocks
        self.dtype = dtype
        # quantized block storage (opt-in): validate the knob through
        # THE regime vocabulary (a typo fails with graftnum's
        # regime-vocabulary error, not a KeyError), then reject
        # full-precision spellings — those pools already store blocks
        # in the engine dtype, and routing them here would silently
        # trade their byte-equality pins for a tolerance budget.
        self.block_dtype: Optional[str] = None
        if block_dtype:
            from ..utils.graftnum import regime_of
            regime = regime_of(block_dtype)
            if regime not in KVQ.STORAGE_DTYPES:
                raise ValueError(
                    f"block_dtype={block_dtype!r} is the full-precision "
                    f"regime {regime!r} — the pool already stores blocks "
                    "in the engine dtype there; quantized storage takes "
                    f"one of {sorted(KVQ.STORAGE_DTYPES)}")
            if regime == "fp8" and not KVQ.fp8_supported():
                raise ValueError(
                    "block_dtype='fp8' requires float8_e4m3fn support "
                    "on this backend (ops.kv_quant.fp8_supported() is "
                    "False) — use 'int8' here")
            self.block_dtype = regime
        self.block_regime = self.block_dtype or _REGIME_LABELS.get(
            np.dtype(dtype).name, np.dtype(dtype).name)
        self.allocator = BlockAllocator(num_blocks, block_size,
                                        watermark=watermark,
                                        sanitize=sanitize)
        shape = PA.pool_shape(n_layer, num_blocks, n_kv_head, block_size,
                              head_dim)
        if self.block_dtype is not None:
            self.data = jnp.zeros(shape,
                                  dtype=KVQ.STORAGE_DTYPES[self.block_dtype])
            self.scales = jnp.zeros(
                KVQ.scales_shape(n_layer, num_blocks, n_kv_head),
                dtype=jnp.float32)
        else:
            self.data = jnp.zeros(shape, dtype=dtype)
            self.scales = None
        self._bytes_per_block = self.data.nbytes // shape[1] + (
            0 if self.scales is None
            else self.scales.nbytes // shape[1])
        self._dev_lock = graftsched.rlock("kv_pool.KVBlockPool._dev_lock")
        graftmem.track(self, "data", "pool_codes", self.data)
        if self.scales is not None:
            graftmem.track(self, "scales", "pool_scales", self.scales)
        # grafttier host spill tier (runtime/kv_tier.py), attached via
        # attach_tier — None means cold prefix entries LRU-evict to
        # oblivion exactly as before
        self.tier = None

        # per-instance defs (not the module-level ops directly): each
        # pool owns its jitted-program caches, so ``_cache_size()`` is
        # THIS pool's program count — the recompile-budget certifier
        # pins it per workload, which a function-identity-shared cache
        # would smear across instances. A pool constructs exactly ONE
        # mover family: plain (full precision, below) or ``_q``
        # (quantized, _init_quantized_movers) — never both, so the
        # full-precision jit population is bit-identical to a build
        # without this feature and the byte-equality pins stay pinned
        # to precisely the programs they always covered.
        if self.block_dtype is not None:
            self._compile_watches = self._init_quantized_movers()
            return

        def _gather_impl(pool, tables):
            return PA.gather_kv(pool, tables)

        def _scatter_impl(pool, k, v, tables):
            return PA.scatter_kv(pool, k, v, tables)

        def _scatter_one_rolled(pool, k, v, table_row, roll):
            # admission merge: roll a solo-prefilled row's K/V content
            # along the slot axis (engine left-pad convention — wrap
            # garbage lands in masked pad slots), then scatter the full
            # row. roll/table are traced: one program per solo shape.
            k = jnp.roll(k, roll, axis=-2)
            v = jnp.roll(v, roll, axis=-2)
            return PA.scatter_kv(pool, k, v, table_row[None])

        def _copy_impl(pool, src, dst):
            return PA.copy_blocks(pool, src, dst)

        self._gather = graftscope.instrument(
            jax.jit(_gather_impl), "kv_pool._gather",
            key_fn=_gather_scope_key)
        self._scatter = graftscope.instrument(
            jax.jit(_scatter_impl, donate_argnums=(0,)),
            "kv_pool._scatter", key_fn=_scatter_scope_key)
        self._scatter_row = graftscope.instrument(
            jax.jit(_scatter_one_rolled, donate_argnums=(0,)),
            "kv_pool._scatter_row", key_fn=_scatter_row_scope_key)
        self._copy = graftscope.instrument(
            jax.jit(_copy_impl, donate_argnums=(0,)),
            "kv_pool._copy", key_fn=_copy_scope_key)
        watches = [
            CompileWatch("kv_pool", self._gather),
            CompileWatch("kv_pool", self._scatter),
            CompileWatch("kv_pool", self._scatter_row),
            CompileWatch("kv_pool", self._copy)]
        if self.allocator.sanitize:
            # graftsan free-block poisoner: rewrite each freed block
            # THROUGH the trash-block write path (the same copy mover
            # CoW uses, one block per dispatch so the program shape is
            # the existing [1]-id copy — no new compiled programs under
            # GRAFTSAN beyond this instance's own jit). The content
            # becomes trash-block garbage on device; the authoritative
            # use-after-free TRAP is the host-side table validation in
            # gather/scatter, which raises with the freeing site's
            # provenance.
            def _poison_impl(pool, src, dst):
                return PA.copy_blocks(pool, src, dst)

            self._poison = jax.jit(_poison_impl, donate_argnums=(0,))
            self.allocator._on_free = self._graftsan_poison
            watches.append(CompileWatch("kv_pool", self._poison))
        self._compile_watches = tuple(watches)

    def _init_quantized_movers(self) -> tuple:
        """Construct the ``_q`` jit family for a quantized pool: the
        same four movers, carrying (data, scales) as one donated pair.
        The regime's quantizer is bound at construction (ops.kv_quant),
        so the traced programs contain no regime branching; the gather
        dequantizes into the ENGINE dtype — downstream decode programs
        see exactly the avals the full-precision gather produces and
        stay shared with contiguous serving."""
        out_dtype = self.dtype
        scatter_fn = (KVQ.scatter_kv_int8 if self.block_dtype == "int8"
                      else KVQ.scatter_kv_fp8)

        def _gather_q_impl(data, scales, tables):
            return KVQ.gather_kv_q(data, scales, tables, out_dtype)

        def _scatter_q_impl(data, scales, k, v, tables):
            return scatter_fn(data, scales, k, v, tables)

        def _scatter_row_q_impl(data, scales, k, v, table_row, roll):
            # admission merge, quantized: same roll-then-scatter as the
            # plain family; the full row re-quantizes on the way in.
            k = jnp.roll(k, roll, axis=-2)
            v = jnp.roll(v, roll, axis=-2)
            return scatter_fn(data, scales, k, v, table_row[None])

        def _copy_q_impl(data, scales, src, dst):
            return KVQ.copy_blocks_q(data, scales, src, dst)

        self._gather_q = graftscope.instrument(
            jax.jit(_gather_q_impl), "kv_pool._gather_q",
            key_fn=_gather_q_scope_key)
        self._scatter_q = graftscope.instrument(
            jax.jit(_scatter_q_impl, donate_argnums=(0, 1)),
            "kv_pool._scatter_q", key_fn=_scatter_q_scope_key)
        self._scatter_row_q = graftscope.instrument(
            jax.jit(_scatter_row_q_impl, donate_argnums=(0, 1)),
            "kv_pool._scatter_row_q", key_fn=_scatter_row_q_scope_key)
        self._copy_q = graftscope.instrument(
            jax.jit(_copy_q_impl, donate_argnums=(0, 1)),
            "kv_pool._copy_q", key_fn=_copy_q_scope_key)
        watches = [
            CompileWatch("kv_pool", self._gather_q),
            CompileWatch("kv_pool", self._scatter_q),
            CompileWatch("kv_pool", self._scatter_row_q),
            CompileWatch("kv_pool", self._copy_q)]
        if self.allocator.sanitize:
            # quantized poisoner: trash-copy through copy_blocks_q so
            # the block's SCALE is poisoned along with its codes — a
            # use-after-free gather of a poisoned block dequantizes to
            # trash-block garbage, never to stale real content.
            def _poison_q_impl(data, scales, src, dst):
                return KVQ.copy_blocks_q(data, scales, src, dst)

            self._poison_q = jax.jit(_poison_q_impl, donate_argnums=(0, 1))
            self.allocator._on_free = self._graftsan_poison
            watches.append(CompileWatch("kv_pool", self._poison_q))
        return tuple(watches)

    # -- graftsan (GRAFTSAN=1) -----------------------------------------------

    def _graftsan_poison(self, ids: List[int]) -> None:
        """``BlockAllocator._on_free`` hook: poison each fully-freed
        block by copying the trash block over it (fired outside the
        allocator lock — see ``_notify_freed``)."""
        trash = jnp.asarray([self.trash], jnp.int32)
        with self._dev_lock:
            for b in ids:
                if self.allocator.refcount(b) > 0:
                    continue  # re-allocated between free and poison
                dst = jnp.asarray([b], jnp.int32)
                if self.block_dtype is not None:
                    self.data, self.scales = self._poison_q(
                        self.data, self.scales, trash, dst)
                else:
                    self.data = self._poison(self.data, trash, dst)

    def _graftsan_check_tables(self, tables, op: str,
                               write: bool = False) -> None:
        """Use-after-free trap: every table id a mover touches must be
        the trash block or a live (refcount >= 1) allocation. A freed
        id raises with the freeing site's provenance; a never-allocated
        id is an uninitialized-placement bug. Writes (``write=True``)
        additionally trap on SHARED blocks (refcount > 1): the module
        contract is that writers never mutate a shared block — extension
        into a shared frontier goes through ``cow_copy`` first."""
        alloc = self.allocator
        for b in {int(x) for x in np.asarray(tables).reshape(-1)}:
            if b == self.trash:
                continue
            if not 0 <= b < alloc.num_blocks:
                raise GraftsanError(
                    f"{op} touches out-of-range block id {b} "
                    f"(pool has {alloc.num_blocks} blocks)")
            refs = alloc.refcount(b)
            if refs == 0:
                site = alloc.freed_provenance(b)
                raise GraftsanError(
                    f"use-after-free: {op} touches poisoned block {b}"
                    + (f", freed at {site}" if site
                       else ", which was never allocated"))
            if write and refs > 1:
                with alloc._lock:
                    sites = list(alloc._san_owner.get(b, []))
                raise GraftsanError(
                    f"CoW violation: {op} writes shared block {b} "
                    f"(refcount {refs}, granted at {sites}) without a "
                    "private copy — shared blocks are immutable; "
                    "cow_copy before the first write")

    @classmethod
    def for_engine(cls, engine: DecodeEngine, num_blocks: int,
                   block_size: int = DEFAULT_KV_BLOCK_SIZE,
                   watermark: float = 0.9,
                   sanitize: Optional[bool] = None,
                   block_dtype: Optional[str] = None) -> "KVBlockPool":
        """Build a pool matching an engine's cache geometry. The paged
        path drives the engine's OWN compiled programs on gathered
        views, so the engine must run the plain XLA single-device
        layout: no Pallas decode kernel (fused layout + in-place DMA),
        no stage partitioning (per-stage cache lists), no mesh."""
        if engine._decode_kernel is not None:
            raise NotImplementedError(
                "KV pool paging drives the XLA cache layout; the Pallas "
                "decode kernel owns its fused in-place cache "
                "(decode_kernel='xla' composes)")
        if engine.specs is not None:
            raise NotImplementedError(
                "KV pool paging covers the unstaged engine; staged "
                "per-stage cache lists page in a later PR")
        if engine._mesh is not None:
            raise NotImplementedError(
                "KV pool paging is single-device; mesh decode (tp/ep) "
                "keeps contiguous caches")
        cfg = engine.config
        heads = getattr(cfg, "n_kv_head", cfg.n_head)
        return cls(cfg.n_layer, num_blocks, heads, block_size,
                   cfg.head_dim, engine._cache_seq, dtype=engine.dtype,
                   watermark=watermark, sanitize=sanitize,
                   block_dtype=block_dtype)

    # -- device ops (all under _dev_lock) ------------------------------------

    def gather(self, tables: np.ndarray, length: int) -> KVCache:
        """Contiguous working cache for the tabled rows (a FRESH buffer
        — downstream decode may donate it). ``length`` is the logical
        depth the caller tracks host-side."""
        with self._dev_lock:
            if self.allocator.sanitize:
                self._graftsan_check_tables(tables, "gather")
            tj = jnp.asarray(tables, jnp.int32)
            if self.block_dtype is not None:
                k, v = self._gather_q(self.data, self.scales, tj)
            else:
                k, v = self._gather(self.data, tj)
        return KVCache(k=k, v=v, length=jnp.asarray(length, jnp.int32))

    def scatter(self, cache: KVCache, tables: np.ndarray) -> None:
        with self._dev_lock:
            if self.allocator.sanitize:
                self._graftsan_check_tables(tables, "scatter", write=True)
            tj = jnp.asarray(tables, jnp.int32)
            if self.block_dtype is not None:
                self.data, self.scales = self._scatter_q(
                    self.data, self.scales, cache.k, cache.v, tj)
            else:
                self.data = self._scatter(self.data, cache.k, cache.v, tj)

    def scatter_columns(self, cache: KVCache, tables: np.ndarray,
                        nb_lo: int) -> None:
        """Scatter only table columns ``[nb_lo, NBm)`` of a full-width
        contiguous cache — THE column-offset convention for writing a
        privately-owned tail behind a shared (immutable) prefix, used
        by both the prefix store's insert and the paged runner's
        shared-prefix placement. One program per nb_lo value
        (``scatter_kv`` derives the block size from the view widths) —
        bounded by the store's chunk grid."""
        bs = self.block_size
        sub = KVCache(k=cache.k[..., nb_lo * bs:, :],
                      v=cache.v[..., nb_lo * bs:, :], length=cache.length)
        self.scatter(sub, tables[:, nb_lo:])

    def scatter_row(self, cache: KVCache, table_row: np.ndarray,
                    roll: int) -> None:
        """Merge one solo-prefilled row (content at ``[sp - plen, sp)``)
        into its blocks at logical ``[d - plen, d)`` (``roll = d - sp``,
        the iterbatch admission move)."""
        with self._dev_lock:
            if self.allocator.sanitize:
                self._graftsan_check_tables(table_row, "scatter_row", write=True)
            row_j = jnp.asarray(table_row, jnp.int32)
            roll_j = jnp.asarray(roll, jnp.int32)
            if self.block_dtype is not None:
                self.data, self.scales = self._scatter_row_q(
                    self.data, self.scales, cache.k, cache.v, row_j,
                    roll_j)
            else:
                self.data = self._scatter_row(
                    self.data, cache.k, cache.v, row_j, roll_j)

    def attach_tier(self, tier) -> None:
        """Wire a grafttier host tier (runtime/kv_tier.py) below this
        pool: allocation pressure demotes cold prefix entries into it
        (``BlockAllocator._demote_pressure``) instead of evicting them
        to oblivion, and the prefix store promotes demoted entries back
        on an affinity hit."""
        self.tier = tier
        self.allocator._tier_demote = lambda: tier.demote_lru(self)

    def spill_blocks(self, ids) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Host copies of the RAW storage planes for ``ids`` — the
        tier's demote reader: ``[L, n, 2, Hkv, bs, hd]`` codes plus the
        ``[L, n, 2, Hkv]`` f32 scales for quantized pools (None for
        full-precision pools). Codes spill AS codes, never dequantized
        f32 — a quantized spill moves the narrow bytes (~4x fewer at
        int8) and a demote/promote round trip is bit-exact at the code
        level for every storage regime (no re-quantization drift)."""
        idx = np.asarray(ids, dtype=np.int32)
        with self._dev_lock:
            if self.allocator.sanitize:
                self._graftsan_check_tables(idx, "spill_blocks")
            codes = np.asarray(self.data[:, jnp.asarray(idx)])
            scales = (None if self.scales is None
                      else np.asarray(self.scales[:, jnp.asarray(idx)]))
        return codes, scales

    def fill_blocks(self, ids, codes: np.ndarray,
                    scales: Optional[np.ndarray] = None) -> None:
        """Write spilled raw blocks back into freshly-allocated ids —
        the tier's promote writer: the host copy returns through
        ``jax.device_put`` into the SAME plane slots a scatter would
        fill, byte-identical to the content ``spill_blocks`` captured.
        The target blocks must be privately owned (the promote path
        allocates them at ref=1 before registering the prefix entry) —
        under GRAFTSAN a shared target trips the CoW write trap."""
        idx_np = np.asarray(ids, dtype=np.int32)
        with self._dev_lock:
            if self.allocator.sanitize:
                self._graftsan_check_tables(idx_np, "fill_blocks",
                                            write=True)
            idx = jnp.asarray(idx_np)
            self.data = self.data.at[:, idx].set(
                jax.device_put(codes).astype(self.data.dtype))
            if self.scales is not None:
                self.scales = self.scales.at[:, idx].set(
                    jax.device_put(scales).astype(self.scales.dtype))

    def cow_copy(self, src: int) -> int:
        """Copy-on-write: allocate a private block, copy ``src`` into
        it, and return the new id. The caller retargets its table entry
        and drops its own ref on ``src``."""
        if self.allocator.sanitize:
            self._graftsan_check_tables(np.asarray([src]), "cow_copy")
        dst = self.allocator.alloc(1)[0]
        with self._dev_lock:
            src_j = jnp.asarray([src], jnp.int32)
            dst_j = jnp.asarray([dst], jnp.int32)
            if self.block_dtype is not None:
                self.data, self.scales = self._copy_q(
                    self.data, self.scales, src_j, dst_j)
            else:
                self.data = self._copy(self.data, src_j, dst_j)
        # locked counter bump (locks-pass finding: pools are shared
        # across front ends — the prefix store's insert and a paged
        # runner can CoW concurrently, and a bare += here loses updates)
        self.allocator.note_cow()
        REGISTRY.inc("kv_pool_cow_copies_total")
        return dst

    # -- observability -------------------------------------------------------

    def note_compiles(self) -> None:
        for w in self._compile_watches:
            w.check()

    def note_gauges(self, component: str = "pool") -> None:
        st = self.allocator.stats()
        in_use = st.blocks_in_use - st.blocks_evictable
        # the block-count gauges carry the storage regime as a label so
        # a capacity dashboard can translate blocks to bytes (and tell
        # a quantized pool's 2x block count from a provisioning change)
        REGISTRY.gauge("kv_cache_blocks_in_use", in_use,
                       component=component,
                       block_dtype=self.block_regime)
        REGISTRY.gauge("kv_cache_blocks_total", st.blocks_total,
                       component=component,
                       block_dtype=self.block_regime)
        REGISTRY.gauge("kv_pool_bytes_per_block", self._bytes_per_block,
                       component=component,
                       block_dtype=self.block_regime)
        # graftscope occupancy time series: blocks-in-use over time at
        # the pool's own accounting points, served at /debug/profile
        graftscope.sample("kv_cache_blocks_in_use", in_use,
                          component=component,
                          block_dtype=self.block_regime)
        if self.tier is not None:
            self.tier.note_gauges(component=component)

    def stats(self) -> dict:
        out = {**self.allocator.stats().as_dict(),
               "block_size": self.block_size,
               "blocks_per_row": self.nbm,
               "block_dtype": self.block_regime,
               "bytes_per_block": self._bytes_per_block,
               "graftsan": self.allocator.sanitize}
        if self.tier is not None:
            out["tier"] = self.tier.stats()
        return out


class PagedKVRunner:
    """Solo/batched paged generate: the engine's compiled programs on
    pool-backed storage (same calling convention as
    ``DecodeEngine.generate``; byte-equal output, pinned by
    tests/test_kv_pool.py).

    With ``prefix`` (a pool-backed ``PrefixCachingEngine`` wrapping the
    SAME engine and pool), a prompt whose prefix is stored prefills
    only its suffix AND shares the store's physical blocks in its own
    table — the full-depth duplication the old store paid per entry is
    gone; only the partially-filled frontier block is copy-on-write'd
    (the row will write into it).
    """

    def __init__(self, engine: DecodeEngine, pool: KVBlockPool,
                 prefix=None):
        if pool.max_seq != engine._cache_seq:
            raise ValueError(
                f"pool rows span {pool.max_seq} slots, engine cache is "
                f"{engine._cache_seq} — gathered views must match the "
                "compiled programs' cache width exactly")
        if engine.prefill_chunk:
            raise NotImplementedError(
                "PagedKVRunner prefills monolithically (one scatter per "
                "admission); build the engine without prefill_chunk")
        if prefix is not None:
            if prefix.plain is not engine:
                raise ValueError("prefix must wrap the same DecodeEngine")
            if getattr(prefix, "_pool", None) is not pool:
                raise ValueError(
                    "prefix store must be backed by the same pool "
                    "(pass pool= to PrefixCachingEngine) — block "
                    "sharing is the point")
        self.engine = engine
        self.pool = pool
        self.prefix = prefix
        # one generation at a time: the pool buffer is donated through
        # every scatter, and the allocator's alloc/free pairs must not
        # interleave between concurrent generates. A declared DEVICE
        # lock (it serializes whole device generations by design).
        self._gen_lock = graftsched.lock("kv_pool.PagedKVRunner._gen_lock",
                                         timeout=600.0)

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 pad: Optional[np.ndarray] = None,
                 eos_id: Optional[int] = None) -> GenerateResult:
        eng = self.engine
        ids, batch, prompt_len, key, pad = prepare_generate(
            prompt_ids, max_new_tokens, eng.max_seq, sampling, key, pad=pad)
        alloc = self.pool.allocator
        with self._gen_lock:
            t0 = time.perf_counter()
            prefill_key, decode_key = _split_keys(key)
            run_params = eng._run_params()
            # tables rows cover the full logical row; entries past the
            # owned/shared range are trash (masked garbage)
            logits, tables, owned, shared = self._prefill_tables(
                ids, batch, prompt_len, max_new_tokens, pad, run_params)
            first = select_token(logits, sampling, prefill_key)
            first.block_until_ready()
            t1 = time.perf_counter()
            tracing.record("prefill", t0, t1, batch=batch,
                           prompt_len=prompt_len, paged=True)
            self.pool.note_gauges(component="paged")
            # columns below every row's shared-prefix floor hold
            # IMMUTABLE registry blocks: decode never writes them, so
            # the per-segment scatter narrows to the owned tail — same
            # program key as the prefill placement's narrowed scatter,
            # and the graftsan CoW trap stays precise (a write to a
            # shared block is always a bug, never segment round-trip).
            nb_lo = min((len(s) for s in shared), default=0)
            try:
                return self._decode(run_params, ids, pad, first, tables,
                                    decode_key, max_new_tokens, sampling,
                                    prompt_len, t1 - t0, eos_id, nb_lo)
            finally:
                for row_ids in owned:
                    alloc.free(row_ids)
                for row_ids in shared:
                    alloc.free(row_ids)
                self.pool.note_gauges(component="paged")

    # -- prefill + placement -------------------------------------------------

    def _prefill_tables(self, ids, batch, prompt_len, max_new, pad,
                        run_params):
        """Prefill (through the prefix store when attached), allocate
        each row's blocks, scatter the state. Returns
        ``(last_logits [B, V], tables [B, NBm], owned_ids per row,
        shared_ids per row)``."""
        eng = self.engine
        pool = self.pool
        alloc = pool.allocator
        bs = pool.block_size
        nbm = pool.nbm
        need = alloc.blocks_for(prompt_len + max_new)
        tables = np.full((batch, nbm), pool.trash, dtype=np.int32)
        owned: List[List[int]] = []
        shared: List[List[int]] = []

        use_store = (self.prefix is not None and batch == 1
                     and not pad.any())
        frontier: List[int] = []
        try:
            if use_store:
                logits, cache, keep_ids, hit_depth = \
                    self.prefix.prefill_shared(ids[0])
                # shared full blocks stay shared; a partially-filled
                # frontier block is CoW'd (this row writes into it)
                n_full = hit_depth // bs
                row_shared = list(keep_ids[:n_full])
                shared.append(row_shared)
                row_owned: List[int] = []
                owned.append(row_owned)
                frontier = list(keep_ids[n_full:])
                while frontier:
                    row_owned.append(pool.cow_copy(frontier[0]))
                    alloc.free([frontier.pop(0)])
                row_owned.extend(alloc.alloc(need - n_full - len(row_owned)))
                tables[0, :n_full] = row_shared
                tables[0, n_full:need] = row_owned
                # scatter ONLY the privately owned tail: shared prefix
                # blocks already hold these bytes (the walk gathered
                # from them) and registry blocks are immutable by
                # contract
                pool.scatter_columns(cache, tables, n_full)
            else:
                ids_j = jnp.asarray(ids, dtype=jnp.int32)
                pad_j = jnp.asarray(pad) if pad.any() else None
                logits, cache = eng._prefill(run_params, ids_j, pad_j)
                for b in range(batch):
                    row = alloc.alloc(need)
                    tables[b, :need] = row
                    owned.append(row)
                    shared.append([])
                pool.scatter(cache, tables)
        except BaseException:
            # all-or-nothing: a mid-placement failure (e.g. exhaustion
            # after the CoW copy) must not leak the refs taken so far
            for row_ids in owned:
                alloc.free(row_ids)
            for row_ids in shared:
                alloc.free(row_ids)
            alloc.free(frontier)
            raise
        return logits, tables, owned, shared

    # -- decode --------------------------------------------------------------

    def _decode(self, run_params, ids, pad, first, tables, decode_key,
                max_new_tokens, sampling, prompt_len, prefill_seconds,
                eos_id, nb_lo: int = 0) -> GenerateResult:
        eng = self.engine
        pad_j = jnp.asarray(pad) if pad.any() else None
        t1 = time.perf_counter()
        steps = max_new_tokens
        parts = [np.asarray(first)[:, None]]
        token = first
        segs = eng._segments(prompt_len, steps)
        done = None
        if eos_id is not None:
            segs = _eos_capped_segments(segs)
            done = parts[0][:, 0] == eos_id
        depth = prompt_len
        if steps > 1 and not (done is not None and done.all()):
            step_keys = _step_keys(decode_key, steps - 1)
            used = 0
            for n, window in segs:
                working = self.pool.gather(tables, depth)
                out, working = eng._decode_seg(
                    run_params, token, working, pad_j,
                    step_keys[used:used + n], sampling=sampling,
                    window=window)
                self.pool.scatter_columns(working, tables, nb_lo)
                token = out[:, -1]
                parts.append(np.asarray(out))
                depth += n
                used += n
                if done is not None:
                    done |= (parts[-1] == eos_id).any(axis=1)
                    if done.all():
                        break
        new = np.concatenate(parts, axis=1)
        t2 = time.perf_counter()
        tracing.record("decode", t1, t2, batch=new.shape[0],
                       steps=new.shape[1], paged=True,
                       blocks_held=int(
                           (tables != self.pool.trash).sum()))
        eng._note_compiles()
        self.pool.note_compiles()
        tokens = np.concatenate([ids, new], axis=1)
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=prefill_seconds,
                              decode_seconds=t2 - t1,
                              new_tokens=new.shape[1],
                              decode_steps=new.shape[1] - 1,
                              pad=pad if pad.any() else None)
