"""Speculative decoding: prompt-lookup drafts + one-program greedy verify.

Latency optimization with **no reference counterpart** (the reference
forwards one token per two HTTP round-trips, reference server.py:169-181;
this module emits up to ``draft_len + 1`` tokens per forward). Greedy
speculative decoding is *provably token-exact*: a draft token is kept only
when it equals the model's own argmax at that position, so the emitted
stream is byte-identical to plain greedy decode — the parity test pins
this (tests/test_spec_decode.py). Sample mode is *distribution-exact* via
rejection sampling against the point-mass draft (see ``_loop_impl``),
reproducing the reference's temperature/top-k sampler distribution
(reference server.py:187-205) token for token — pinned by a pmf test.

Why it pays on TPU: single-stream decode is HBM-bandwidth-bound — every
step streams all weights to produce ONE token's worth of MXU work. A
verify step forwards ``K+1`` tokens through the same weights for the same
weight traffic, so each accepted draft is a nearly-free token. With
prompt-lookup drafting (Saxena's "prompt lookup decoding" /
assisted-generation n-gram variant) the draft model is the sequence
itself — no second network:

- **draft**: find the most recent previous occurrence of the last
  ``ngram`` tokens in the sequence so far; propose the ``draft_len``
  tokens that followed it (natural text and greedy GPT-2 output are both
  highly repetitive, so acceptance is high exactly when decode is long);
- **verify**: one cached forward of ``[t_last, d_1..d_K]`` at the current
  cache offset (ops.attention.cached_attention already supports S>1
  writes at a dynamic offset); accept the longest prefix where
  ``d_j == argmax(logits_{j-1})``, emit one bonus token from the first
  mismatch position;
- **rewind**: the KV written for rejected drafts is logically dropped by
  resetting ``KVCache.length`` (a traced scalar) — the stale slots sit
  beyond the valid length, are masked out of attention by ``kv_length``,
  and are physically overwritten by the next verify step's write at the
  rewound offset.

The whole generation after prefill is ONE compiled program: a
``lax.while_loop`` whose body is draft-match (vectorized n-gram scan, no
host work) + verify forward + buffer/cache bookkeeping.

Batched speculation (the spec x batching composition): rows accept
*different* draft counts per verify, which would naively need per-row
cache write offsets — impossible under one ``dynamic_update_slice``. The
batched loop keeps every row at ONE uniform cache depth instead
(the iterbatch trick, inverted): row i's content occupies slots
``[pad_i, total)`` with per-row left-pad slack, every verify forwards
``[t_last_i, drafts_i]`` for all rows at the shared offset, and after
per-row acceptance the batch RE-SYNCS — each row's cache/buffer rolls by
a signed per-row shift so all rows end at the new uniform depth
``max_i(content_len_i)``, the slack landing in the masked pad prefix.
The roll is a pure permutation (values bitwise intact, positions =
slot - pad_i unchanged), so each row's stream is byte-equal to its solo
single-stream spec run — greedy AND seeded sample (per-row key chains
advance one split per verify, exactly like the solo loop). Acceptance
counts are traced values inside one ``lax.while_loop`` program: the
compiled-program set stays one loop per (batch width, policy), never one
per acceptance pattern. The minimal uniform depth also preserves the
single-stream headroom bound: writes never pass
``max_i(plen_i + max_new) + draft_len <= max_seq``.

``seg_verify`` exposes the same body as a bounded SEGMENT program
(per-row budgets, at most ``max_verify`` verifies) so the iteration-level
scheduler (runtime.iterbatch) can run speculative segments on a live
batch — rows join/retire between segments without draining the batch.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config, Params
from ..ops.attention import KVCache
from ..utils import graftmem, graftsched, graftscope, tracing
from ..utils.metrics import REGISTRY, CompileWatch
from .engine import (DecodeEngine, GenerateResult, SamplingConfig,
                     prepare_generate, sampler_pmf, select_token)


# Static-analysis contract (tools/graftcheck): every ``jax.jit`` site in
# this module, by holding attribute — enumerated by the recompile-budget
# certifier; an undeclared site is a lint finding.
JIT_ENTRY_POINTS = ("_loop", "_loop_b", "_seg_b")

# Observability contract (tools/graftcheck scope pass + utils/graftscope):
# every declared jit entry point's dispatch is timed into the graftscope
# ring (graftscope.instrument at the jit site), keyed in the certifier's
# program-key model (recompile.spec_call_keys / iter_spec_segment_keys).
PROFILED_SCOPES = ("_loop", "_loop_b", "_seg_b")


# graftscope program-key derivations (the certifier's model: _loop ->
# (max_new, sampling, pad present); _loop_b -> (b, max_new, sampling);
# _seg_b -> (width, max_verify, sampling) — acceptance counts and
# budgets are traced and never key programs)

def _loop_scope_key(params, first_token, cache, buf, total, key, pad, *,
                    max_new, sampling):
    return (max_new, sampling, pad is not None)


def _loop_b_scope_key(params, first, cache, buf, total, keys, pad, *,
                      max_new, sampling):
    return (int(first.shape[0]), max_new, sampling)


def _seg_b_scope_key(params, buf, cache, total, pad, keys, budgets, *,
                     max_verify, sampling):
    return (int(buf.shape[0]), max_verify, sampling)

# Donation contract (tools/graftcheck sanitize pass): consumed
# positional arguments per entry point. ``_loop``/``_loop_b`` donate
# the prefill cache (and the batched token buffer); ``_seg_b`` donates
# the segment's token buffer and working cache — the iteration
# scheduler must re-bind both from the call's outputs every segment.
DONATED_ARGS = {"_loop": (2,), "_loop_b": (2, 3), "_seg_b": (1, 2)}

# Lock-discipline contract (tools/graftcheck locks pass): the
# acceptance accounting ThreadingHTTPServer callers and the iteration
# scheduler both bump lives under ``_stats_lock`` — including the
# cross-module ``spec._requests`` retirement count in
# runtime/iterbatch.py, which this declaration holds to the same lock.
GUARDED_STATE = {"_requests": "_stats_lock", "_verifies": "_stats_lock",
                 "_emitted": "_stats_lock"}
LOCK_ORDER = ("_stats_lock",)

# Block-handoff contract for pool-backed schedulers (see
# ``_seg_b_impl``): True means a spec segment may rewrite ANY slot of a
# row's cache (the re-sync roll), so paged storage must scatter whole
# rows back, never just the newly decoded columns.
SEG_REWRITES_FULL_CACHE = True

# HBM-ledger contract (tools/graftcheck memory pass + utils/graftmem):
# the verify loop's device token buffer ``[.., max_seq + draft_len + 1]``
# — live from allocation to the post-loop numpy fetch (solo and batched
# paths each register their own handle-keyed entry; the iteration
# scheduler's per-batch spec buffer registers in runtime/iterbatch.py).
MEMORY_LEDGER = {
    "buf": "spec_buffers",
}


class SpecDecodeEngine:
    """Speculative decode engine (single stream; greedy + sample modes).

    Composes a ``DecodeEngine`` for parameter preparation (dtype cast /
    int8 quantization / model-family dispatch) and its jitted prefill;
    replaces the token-by-token decode scan with the verify loop above.

    ``draft_len`` (K) is the speculation depth: each verify forward costs
    one (K+1)-token step and emits 1..K+1 tokens. ``ngram`` is the match
    width for prompt lookup (2 is the standard sweet spot: long enough to
    avoid noise matches, short enough to fire often).
    """

    def __init__(self, params: Params, config: GPT2Config, max_seq: int,
                 dtype=jnp.float32, draft_len: int = 6, ngram: int = 2,
                 prefill_chunk: Optional[int] = None):
        from ..models import is_window_independent
        if not is_window_independent(config):
            # Not an implementation gap — a semantic one: a (K+1)-token
            # verify forward must route identically to the plain engine's
            # single-token steps for the token-exactness guarantee to
            # hold (see models.is_window_independent).
            raise NotImplementedError(
                "speculative decoding requires window-independent token "
                "routing; MoE capacity-factor routing makes multi-token "
                "verify windows route differently than single-token "
                "decode steps — serve MoE with the plain engine")
        if draft_len < 1:
            raise ValueError("draft_len must be >= 1")
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        self.draft_len = draft_len
        self.ngram = ngram
        # The engine owns params/cache sizing (and chunked prefill); its
        # overflow guard also covers ours (we re-check with draft headroom
        # in generate()). decode_kernel is pinned to "xla" on BOTH sides:
        # the verify windows are multi-token (fused-XLA numerics), so a
        # kernel-decoding plain engine would break the token-exactness
        # contract between the spec stream and the plain fallback stream
        # on argmax near-ties.
        self._eng = DecodeEngine(params, config, max_seq, dtype=dtype,
                                 prefill_chunk=prefill_chunk,
                                 decode_kernel="xla")
        self.config = config
        self.max_seq = max_seq
        self._stats_lock = graftsched.lock(
            "spec_decode.SpecDecodeEngine._stats_lock")
        self._requests = 0
        self._verifies = 0
        self._emitted = 0
        self._loop = graftscope.instrument(
            jax.jit(self._loop_impl,
                    static_argnames=("max_new", "sampling"),
                    donate_argnums=(2,)),
            "spec_decode._loop", key_fn=_loop_scope_key)
        # Batched variants (one program per batch width + policy, never
        # per acceptance pattern): the full-generation loop and the
        # bounded segment program the iteration scheduler drives.
        self._loop_b = graftscope.instrument(
            jax.jit(self._loop_b_impl,
                    static_argnames=("max_new", "sampling"),
                    donate_argnums=(2, 3)),
            "spec_decode._loop_b", key_fn=_loop_b_scope_key)
        self._seg_b = graftscope.instrument(
            jax.jit(self._seg_b_impl,
                    static_argnames=("max_verify", "sampling"),
                    donate_argnums=(1, 2)),
            "spec_decode._seg_b", key_fn=_seg_b_scope_key)
        # compile-event accounting (one increment per NEW (width, policy)
        # program — see utils.metrics.CompileWatch); the iteration
        # scheduler checks the segment watch after its dispatches
        self._compile_watches = (CompileWatch("spec_loop", self._loop),
                                 CompileWatch("spec_loop", self._loop_b),
                                 CompileWatch("spec_seg", self._seg_b))

    def _note_compiles(self) -> None:
        self._eng._note_compiles()   # the shared prefill programs
        for w in self._compile_watches:
            w.check()
        REGISTRY.gauge("jit_program_cache_size",
                       sum(w.seen() for w in self._compile_watches),
                       component="spec")

    def _update_stats(self, n_req: int, n_tok: int, steps: int) -> None:
        """Shared acceptance accounting: cumulative /healthz stats,
        counters, and the live acceptance-rate gauge."""
        with self._stats_lock:
            self._requests += n_req
            self._verifies += steps
            self._emitted += n_tok
            rate = self._emitted / max(self._verifies, 1)
        REGISTRY.inc("spec_verify_steps_total", value=steps)
        REGISTRY.inc("spec_emitted_tokens_total", value=n_tok)
        REGISTRY.gauge("spec_acceptance_rate", round(rate, 4))

    @property
    def plain(self) -> DecodeEngine:
        """The wrapped plain engine (shared weights/compilations) — the
        serving layer routes ineligible requests here."""
        return self._eng

    def check_request(self, prompt_len: int, max_new_tokens: int) -> None:
        """Raising form of the speculation-eligibility predicate, THE
        single definition of the rule: the batching front ends
        (runtime.batcher, runtime.iterbatch) call it on the caller
        thread so a spec-flagged request the verify loop cannot serve
        exactly is refused with its own numbers, never discovered
        mid-batch — and a future change to the rule (e.g. an alignment
        reserve) cannot silently diverge between front ends."""
        if prompt_len < self.ngram:
            raise ValueError(
                f"prompt_len={prompt_len} shorter than ngram={self.ngram}")
        total = prompt_len + max_new_tokens + self.draft_len
        if total > self.max_seq:
            raise ValueError(
                f"prompt_len={prompt_len} + max_new_tokens="
                f"{max_new_tokens} + draft_len={self.draft_len} "
                f"exceeds max_seq={self.max_seq}; verify writes need "
                "draft_len slots of headroom")

    def eligible(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Boolean form of ``check_request``: prompt long enough for an
        n-gram and ``draft_len`` slots of cache headroom for verify
        writes. The serving router and the prefix-cache front end both
        consult this (a request that fails it decodes plain)."""
        try:
            self.check_request(prompt_len, max_new_tokens)
            return True
        except ValueError:
            return False

    def stats(self) -> dict:
        """Cumulative speculation effectiveness (served at /healthz)."""
        with self._stats_lock:
            return {"requests": self._requests,
                    "verify_steps": self._verifies,
                    "emitted_tokens": self._emitted,
                    "draft_len": self.draft_len,
                    "tokens_per_verify": round(self._emitted
                                               / max(self._verifies, 1), 2)}

    # -- shared verify-step pieces (solo loop + batched loop/segment) --------

    def _draft_row(self, buf, low, total, t_last):
        """Propose K tokens for ONE row via most-recent n-gram match over
        ``buf[low:total)`` (``low`` excludes the left-pad prefix — pad
        garbage must never become draft material). THE draft definition:
        the solo loop calls it with scalars, the batched paths vmap it
        with per-row ``low``/``t_last`` — same ops, so a batched row's
        drafts are bitwise its solo run's."""
        K, ngram = self.draft_len, self.ngram
        buflen = buf.shape[0]
        j_arr = jnp.arange(buflen, dtype=jnp.int32)
        last = jax.lax.dynamic_slice(buf, (total - ngram,), (ngram,))
        match = jnp.ones((buflen,), dtype=bool)
        for t in range(ngram):
            match = match & (jnp.roll(buf, -t) == last[t])
        # exclude the current occurrence itself, anything past it,
        # and the left-pad prefix
        match = match & (j_arr < total - ngram) & (j_arr >= low)
        cand = jnp.where(match, j_arr, -1)
        best = cand.max()
        found = best >= 0
        start = jnp.where(found, best + ngram, 0)
        got = jax.lax.dynamic_slice(buf, (start,), (K,))
        # fallback: repeat the last token (catches token-loop output)
        return jnp.where(found, got, jnp.full((K,), t_last, jnp.int32))

    def _accept_patch(self, logits, drafts, step_key,
                      sampling: SamplingConfig):
        """[K+1, V] verify logits -> (n_accept, patch_tokens [K+1]).

        ``patch_tokens[j]`` is meaningful for ``j <= n_accept``:
        accepted drafts then the bonus token. One row's acceptance —
        shared verbatim between the solo loop and the vmapped batched
        paths (vmapped per-row RNG draws consume the same bits a solo
        call with that row's key would — the select_token per-row-key
        contract)."""
        K = self.draft_len
        if sampling.mode == "greedy":
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            hits = (drafts == greedy[:K]).astype(jnp.int32)
            # greedy[j] is the token after x[j]; the bonus at the first
            # mismatch position is greedy itself, so patch == greedy
            return jnp.cumprod(hits).sum(), greedy
        # THE sampler distribution (engine.sampler_pmf: temperature +
        # top-k + optional nucleus) — shared with select_token so
        # acceptance probabilities and the plain sampler cannot drift
        probs, top_idx = sampler_pmf(logits, sampling)   # [K+1, k]
        k_acc, k_res = jax.random.split(step_key)
        in_topk = top_idx[:K] == drafts[:, None]         # [K, k]
        p_d = (probs[:K] * in_topk).sum(-1)              # [K]
        u = jax.random.uniform(k_acc, (K,))
        n_accept = jnp.cumprod((u < p_d).astype(jnp.int32)).sum()
        # bonus from row n_accept: the residual when a rejection
        # happened there, the plain pmf when every draft was accepted
        row_p, row_i = probs[n_accept], top_idx[n_accept]
        d_rej = drafts[jnp.minimum(n_accept, K - 1)]
        zero_d = (n_accept < K) & (row_i == d_rej)
        resid = jnp.where(zero_d, 0.0, row_p)
        choice = jax.random.categorical(k_res, jnp.log(resid))
        bonus = row_i[choice].astype(jnp.int32)
        dr_ext = jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)])
        return n_accept, jnp.where(jnp.arange(K + 1) < n_accept,
                                   dr_ext, bonus)

    # -- compiled verify loop ------------------------------------------------

    def _loop_impl(self, params, first_token, cache, buf, total, key, pad, *,
                   max_new: int, sampling: SamplingConfig):
        """(buf, total, cache) after prefill -> (buf, verify_steps).

        ``pad`` is ``None`` or a ``[1]`` int32 array: the left-pad prefix
        the chunk-aligned prefill placed in ``buf``/cache slots ``[0,
        pad)`` — masked as attention keys and excluded from the n-gram
        draft search (chunk padding must never become draft material).

        Invariant at loop entry: ``buf[:total]`` holds prompt + emitted
        tokens, ``cache.length == total - 1`` (the last emitted token has
        not been forwarded yet), ``emitted`` counts new tokens so far.

        Greedy acceptance compares drafts against the model argmax —
        token-exact by construction. Sample mode is *distribution-exact*
        rejection sampling against the point-mass draft: draft ``d_j`` is
        accepted with probability ``p_j(d_j)`` under the reference
        sampler's temperature/top-k pmf; the first rejection's bonus token
        is drawn from the residual ``p_j`` with ``d_j`` zeroed and
        renormalized (for a point-mass proposal the Leviathan residual
        ``max(0, p - q)/Z`` reduces to exactly that), and a fully-accepted
        window draws the bonus from ``p_K`` unmodified. Each emitted token
        is therefore distributed exactly as the plain sampler's — only the
        RNG consumption pattern differs, so seeded streams differ while
        the distribution does not (pinned by the pmf test)."""
        K = self.draft_len

        low = jnp.int32(0) if pad is None else pad[0]

        def body(carry):
            buf, total, cache, emitted, steps, key = carry
            key, step_key = jax.random.split(key)
            t_last = buf[total - 1]
            drafts = self._draft_row(buf, low, total, t_last)
            x = jnp.concatenate([t_last[None], drafts])[None, :]  # [1, K+1]
            logits, cache = self._eng._forward_cached(params, x, cache, pad)
            n_accept, patch_tokens = self._accept_patch(logits[0], drafts,
                                                        step_key, sampling)
            n_emit = jnp.minimum(n_accept + 1, max_new - emitted)
            # splice the emitted tokens into buf at `total`
            old = jax.lax.dynamic_slice(buf, (total,), (K + 1,))
            patch = jnp.where(jnp.arange(K + 1) < n_emit, patch_tokens, old)
            buf = jax.lax.dynamic_update_slice(buf, patch, (total,))
            # rewind: forwarded-and-kept = t_last + the accepted prefix;
            # slots beyond are stale and masked by kv_length until the
            # next verify overwrites them at the rewound offset
            cache = cache._replace(
                length=(total - 1 + n_emit).astype(jnp.int32))
            return (buf, total + n_emit, cache, emitted + n_emit,
                    steps + 1, key)

        def cond(carry):
            return carry[3] < max_new

        first = first_token.reshape(()).astype(jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, first[None], (total,))
        carry = (buf, total + 1, cache, jnp.int32(1), jnp.int32(0), key)
        buf, _, cache, _, steps, _ = jax.lax.while_loop(cond, body, carry)
        return buf, steps, cache

    # -- batched verify loop -------------------------------------------------

    @staticmethod
    def _roll_cache_rows(cache, shifts):
        """Per-row signed roll of every cache buffer along the slot axis
        (``out[.., b, .., j, :] = in[.., b, .., j - shifts[b], :]``, mod
        buffer size) — the batched rewind/re-sync permutation. A pure
        gather: values stay bitwise intact, and since a row's positions
        are ``slot - pad`` with pad shifted by the same amount, the
        row's math is untouched. Handles plain, fused (placeholder
        ``v``), and staged (list) cache forms. ``shifts`` is traced —
        one compiled gather serves every acceptance pattern."""
        def g(x):
            if getattr(x, "ndim", 0) <= 1:
                return x                           # fused placeholder v
            s = x.shape[-2]
            idx = (jnp.arange(s)[None, :] - shifts[:, None]) % s  # [B, S]
            shape = (1, idx.shape[0]) + (1,) * (x.ndim - 4) + (s, 1)
            return jnp.take_along_axis(x, idx.reshape(shape), axis=-2)

        def one(c: KVCache) -> KVCache:
            return KVCache(k=g(c.k), v=g(c.v), length=c.length)

        if isinstance(cache, list):
            return [one(c) for c in cache]
        return one(cache)

    def _step_b(self, params, sampling: SamplingConfig, budgets, carry):
        """One batched verify step + per-row rewind/re-sync: the body of
        both batched programs (full loop and iterbatch segment).

        Carry: ``(buf [B, buflen], total, cache, pad [B], emitted [B],
        steps, keys [B, 2])``. Invariant (the solo loop's, per row at
        ONE uniform depth): row b's content is ``buf[b, pad_b:total]``,
        ``cache.length == total - 1`` with slots ``[pad_b, total - 1)``
        valid for row b, and the last emitted token is unforwarded.

        ``budgets`` [B] cap each row's TOTAL emission (ghost/finished
        rows run n_emit = 0 and just carry garbage nobody reads); the
        cap is the same ``min(n_accept + 1, remaining)`` the solo loop
        applies at max_new, so a capped row's stream is byte-equal to a
        solo run with that budget. After acceptance the batch re-syncs
        at the MINIMAL uniform depth ``max_b(content_len_b)`` — pads
        absorb the per-row slack, so depth never exceeds the longest
        row's content and verify writes keep the single-stream headroom
        bound (``max(plen + budget) + draft_len``)."""
        buf, total, cache, pad, emitted, steps, keys = carry
        K = self.draft_len
        b, buflen = buf.shape
        if sampling.mode == "greedy":
            step_keys = keys                       # program never reads them
        else:
            pair = jax.vmap(jax.random.split)(keys)        # [B, 2, 2]
            keys, step_keys = pair[:, 0], pair[:, 1]
        t_last = buf[:, total - 1]                         # [B]
        drafts = jax.vmap(
            lambda bf, lo, tl: self._draft_row(bf, lo, total, tl))(
                buf, pad, t_last)                          # [B, K]
        x = jnp.concatenate([t_last[:, None], drafts], axis=1)  # [B, K+1]
        logits, cache = self._eng._forward_cached(params, x, cache, pad)
        n_accept, patch = jax.vmap(
            lambda lg, dr, sk: self._accept_patch(lg, dr, sk, sampling))(
                logits, drafts, step_keys)
        n_emit = jnp.clip(n_accept + 1, 0, budgets - emitted)     # [B]
        old = jax.lax.dynamic_slice(buf, (0, total), (b, K + 1))
        write = jnp.where(jnp.arange(K + 1)[None, :] < n_emit[:, None],
                          patch, old)
        buf = jax.lax.dynamic_update_slice(buf, write, (0, total))
        # rewind + re-sync: row b keeps n_emit_b of the K+1 verify slots
        # (t_last + accepted prefix — the solo loop's length formula),
        # then every row rolls by a signed per-row shift so content ends
        # at the new uniform depth; the slack lands in the masked pad
        # prefix and stale verify slots sit beyond the new length until
        # the next verify overwrites them.
        content = (total - pad) + n_emit                   # [B] new lens
        new_total = content.max()
        new_pad = new_total - content                      # [B] >= 0
        shifts = new_pad - pad                             # signed
        bidx = (jnp.arange(buflen)[None, :] - shifts[:, None]) % buflen
        buf = jnp.take_along_axis(buf, bidx, axis=1)
        cache = self._roll_cache_rows(cache, shifts)
        new_len = (new_total - 1).astype(jnp.int32)
        if isinstance(cache, list):
            cache = [c._replace(length=new_len) for c in cache]
        else:
            cache = cache._replace(length=new_len)
        return (buf, new_total, cache, new_pad, emitted + n_emit,
                steps + 1, keys)

    def _loop_b_impl(self, params, first, cache, buf, total, keys, pad, *,
                     max_new: int, sampling: SamplingConfig):
        """Batched full-generation loop: ``(buf, pad, total, steps,
        cache)`` after prefill -> completion. Entry state mirrors the
        solo loop per row: ``buf[b, pad_b:total]`` holds row b's prompt,
        ``cache.length == total`` from prefill, ``first`` [B] are the
        prefill-selected tokens (appended here, making ``cache.length ==
        total' - 1``). Runs until EVERY row emitted ``max_new``; rows
        that finish early keep verifying as ghosts (n_emit = 0, content
        frozen) — harmless by row independence."""
        b = buf.shape[0]
        first = first.reshape((b,)).astype(jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, first[:, None], (0, total))
        budgets = jnp.full((b,), max_new, jnp.int32)
        carry = (buf, total + 1, cache, pad,
                 jnp.ones((b,), jnp.int32), jnp.int32(0), keys)

        def cond(c):
            return jnp.any(c[4] < max_new)

        def body(c):
            return self._step_b(params, sampling, budgets, c)

        buf, total, cache, pad, _, steps, _ = jax.lax.while_loop(
            cond, body, carry)
        return buf, pad, total, steps, cache

    def _seg_b_impl(self, params, buf, cache, total, pad, keys, budgets, *,
                    max_verify: int, sampling: SamplingConfig):
        """Bounded draft-verify SEGMENT over a live batch (the
        iteration-level scheduler's spec segment type): up to
        ``max_verify`` verify steps, stopping early when every row's
        remaining ``budgets`` [B] are spent. Returns ``(buf, total,
        cache, pad, emitted [B], steps, keys)`` — the same carry it
        takes, so segments resume exactly where the last one stopped
        (per-row key chains included: a row's verify sequence across
        segments is identical to its uninterrupted solo run).

        Paged-KV block handoff contract (runtime.kv_pool x
        runtime.iterbatch): the per-row rewind/re-sync inside
        ``_step_b`` ROLLS entire cache rows (``_roll_cache_rows`` — a
        permutation of every slot, not an append at the frontier), so a
        pool-backed scheduler must scatter the FULL row back into its
        blocks after each spec segment; a new-columns-only handoff
        would silently keep pre-roll bytes for the untouched blocks.
        ``SEG_REWRITES_FULL_CACHE`` declares this; iterbatch asserts it
        before choosing its scatter range."""
        b = buf.shape[0]
        carry = (buf, total, cache, pad,
                 jnp.zeros((b,), jnp.int32), jnp.int32(0), keys)

        def cond(c):
            return (c[5] < max_verify) & jnp.any(c[4] < budgets)

        def body(c):
            return self._step_b(params, sampling, budgets, c)

        return jax.lax.while_loop(cond, body, carry)

    # -- public API ----------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 pad: Optional[np.ndarray] = None,
                 delivered: Optional[tuple] = None) -> GenerateResult:
        """Speculative generate: per row token-exact vs
        ``DecodeEngine.generate`` in greedy mode, distribution-exact
        (rejection sampling, see ``_loop_impl``) in sample mode.

        Accepts ``[S]`` / ``[1, S]`` single streams (the original loop,
        byte-for-byte unchanged), ``[B, S]`` batches, and ragged prompt
        lists (left-padded); ``pad`` lets pre-padded callers
        (runtime.batcher) declare their left-pad prefixes, exactly like
        the plain engine. Batched rows are byte-equal to their solo
        spec runs: greedy by construction, seeded sampling via per-row
        key chains (``key`` must then be a ``[B, 2]`` per-row stack —
        each row's stream is a function of its own key only).

        ``delivered`` (optional ``(requests, tokens)``) overrides the
        acceptance-stats accounting for bucketing front ends
        (runtime.batcher): a bucketed round decodes dummy rows and
        over-decodes short requests to the shared step count, but
        /healthz's ``tokens_per_verify`` must count what callers were
        actually served, or the admission and iteration schedulers
        would report incompatible numbers for the same metric.
        """
        # the spec flag is routing metadata for the batching front ends;
        # normalize it away so flagged and unflagged requests share the
        # same compiled programs (and identical token streams)
        sampling = dataclasses.replace(sampling, spec=False)
        ids, batch, prompt_len, key, pad = prepare_generate(
            prompt_ids, max_new_tokens, self.max_seq, sampling, key,
            allow_ragged=True, pad=pad)
        min_plen = prompt_len - (int(pad.max()) if pad.any() else 0)
        if min_plen < self.ngram:
            raise ValueError(
                f"prompt_len={min_plen} shorter than ngram={self.ngram}")
        # Verify steps write up to draft_len tokens past the final length,
        # so the cache/position headroom check is stricter than the
        # engine's prompt+new <= max_seq guard. (The batched loop's
        # uniform depth never exceeds the longest row's content length —
        # see _step_b — so the single-stream bound covers batches too.)
        total_max = prompt_len + max_new_tokens + self.draft_len
        if total_max > self.max_seq:
            raise ValueError(
                f"prompt_len + max_new_tokens + draft_len = {total_max} "
                f"exceeds max_seq={self.max_seq}; verify writes need "
                "draft_len slots of headroom")
        if (batch > 1 and sampling.mode != "greedy"
                and getattr(key, "ndim", 1) != 2):
            raise ValueError(
                "batched sample-mode speculation needs a [B, 2] per-row "
                "key stack (one key per row — the engine._split_keys "
                "contract; a single joint key cannot be byte-equal to "
                "per-row solo runs)")

        # Chunk-align through the inner engine's shared helper; reserve
        # covers upcoming tokens AND the verify write headroom.
        ids, pad, prompt_len, chunk = self._eng._align_chunks(
            ids, pad, prompt_len, reserve=max_new_tokens + self.draft_len)

        ids_j = jnp.asarray(ids, dtype=jnp.int32)
        pad_j = jnp.asarray(pad) if pad.any() else None
        run_params = self._eng._run_params()

        t0 = time.perf_counter()
        if batch == 1:
            if getattr(key, "ndim", 1) == 2:
                key = key[0]     # a 1-row per-row stack == the solo key
            prefill_key, loop_key = jax.random.split(key)
        elif sampling.mode == "greedy":
            prefill_key = key                    # never consumed by greedy
            loop_key = jnp.zeros((batch, 2), jnp.uint32)
        else:
            pair = jax.vmap(jax.random.split)(key)       # [B, 2, 2]
            prefill_key, loop_key = pair[:, 0], pair[:, 1]
        if chunk:
            n_chunks = ids_j.shape[1] // chunk
            chunks = ids_j.reshape(batch, n_chunks, chunk).transpose(1, 0, 2)
            last_logits, cache = self._eng._prefill_chunked(
                run_params, chunks,
                pad_j if pad_j is not None
                else jnp.zeros((batch,), jnp.int32))
        else:
            last_logits, cache = self._eng._prefill(run_params, ids_j, pad_j)
        first = select_token(last_logits, sampling, prefill_key)
        first.block_until_ready()
        t1 = time.perf_counter()
        tracing.record("prefill", t0, t1, batch=batch,
                       prompt_len=prompt_len, chunked=bool(chunk))

        if batch == 1:
            return self.run_loop(run_params, ids_j[0], first, cache,
                                 prompt_len, loop_key, max_new_tokens,
                                 sampling, prefill_seconds=t1 - t0,
                                 pad=pad if pad.any() else None,
                                 delivered=delivered)
        return self._run_loop_batched(run_params, ids_j, first, cache,
                                      prompt_len, loop_key, pad,
                                      max_new_tokens, sampling,
                                      prefill_seconds=t1 - t0,
                                      delivered=delivered)

    def _run_loop_batched(self, run_params, ids_j, first, cache,
                          prompt_len: int, loop_keys, pad,
                          max_new_tokens: int, sampling: SamplingConfig,
                          prefill_seconds: float = 0.0,
                          delivered: Optional[tuple] = None
                          ) -> GenerateResult:
        """Run the batched verify loop off a prepared batched prefill
        state and assemble the result. ``pad`` [B] numpy is each row's
        left-pad prefix (bucket pad and/or ragged left_pad). The loop's
        re-syncs keep the batch at the MINIMAL uniform depth, so a pad
        shared by every row is slid out: the final pad is
        ``pad_b - min(pad)`` (row content still exactly
        ``prompt + max_new`` tokens) — the RETURNED pads are the ones
        reported for output stripping, never the input ones."""
        batch = ids_j.shape[0]
        t1 = time.perf_counter()
        buf = jnp.zeros((batch, self.max_seq + self.draft_len + 1),
                        jnp.int32)
        mem_h = graftmem.track(self, "buf", "spec_buffers", buf)
        buf = jax.lax.dynamic_update_slice(buf, ids_j, (0, 0))
        buf, pad_out, total, steps, _ = self._loop_b(
            run_params, first, cache, buf, jnp.int32(prompt_len),
            loop_keys, jnp.asarray(pad, dtype=jnp.int32),
            max_new=max_new_tokens, sampling=sampling)
        buf = np.asarray(jax.block_until_ready(buf))
        graftmem.release(mem_h)  # device buffer fetched; entry retires
        pad_np = np.asarray(pad_out).astype(np.int32)
        total_i = int(total)
        t2 = time.perf_counter()

        steps_i = int(steps)
        n_req, n_tok = (delivered if delivered is not None
                        else (batch, batch * max_new_tokens))
        self._update_stats(n_req, n_tok, steps_i)
        tracing.record("decode", t1, t2, spec=True, batch=batch,
                       verify_steps=steps_i,
                       emitted=batch * max_new_tokens)
        self._note_compiles()

        tokens = buf[:, :total_i]
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=prefill_seconds,
                              decode_seconds=t2 - t1,
                              new_tokens=max_new_tokens,
                              decode_steps=max_new_tokens - 1,
                              verify_steps=steps_i,
                              pad=pad_np if pad_np.any() else None)

    def run_loop(self, run_params, prompt_row, first, cache,
                 prompt_len: int, loop_key, max_new_tokens: int,
                 sampling: SamplingConfig,
                 prefill_seconds: float = 0.0,
                 pad=None,
                 delivered: Optional[tuple] = None) -> GenerateResult:
        """Run the compiled verify loop off a prepared prefill state and
        assemble the result — shared by ``generate`` and the prefix-cache
        front end (runtime.prefix_cache), which produces (first, cache)
        its own way. Donates ``cache``; updates speculation stats.

        ``pad`` ([1] numpy, optional) is the single source of the
        left-pad prefix: the loop's device-side mask derives from it, and
        the result reports it for output stripping — one value, no way to
        desync the two uses.

        ``delivered`` is the same served-(requests, tokens) stats
        override ``generate`` documents: a bucketing front end's SOLO
        spec round lands here (batch == 1), and its over-decode past the
        request's own max_new_tokens is shape tax exactly like the
        batched path's — without the override /healthz would count the
        bucketed step total."""
        # front ends (prefix cache, batchers) may pass a spec-flagged
        # policy through; the flag is routing metadata — normalize so
        # flagged and plain calls share one compiled loop per policy
        sampling = dataclasses.replace(sampling, spec=False)
        pad_j = jnp.asarray(pad) if pad is not None and pad.any() else None
        t1 = time.perf_counter()
        buf = jnp.zeros((self.max_seq + self.draft_len + 1,), jnp.int32)
        mem_h = graftmem.track(self, "buf", "spec_buffers", buf)
        buf = jax.lax.dynamic_update_slice(
            buf, jnp.asarray(prompt_row, dtype=jnp.int32), (0,))
        buf, steps, _ = self._loop(run_params, first[0], cache, buf,
                                   jnp.int32(prompt_len), loop_key, pad_j,
                                   max_new=max_new_tokens, sampling=sampling)
        buf = np.asarray(jax.block_until_ready(buf))
        graftmem.release(mem_h)  # device buffer fetched; entry retires
        t2 = time.perf_counter()

        steps_i = int(steps)
        n_req, n_tok = (delivered if delivered is not None
                        else (1, max_new_tokens))
        self._update_stats(n_req, n_tok, steps_i)
        tracing.record("decode", t1, t2, spec=True, batch=1,
                       verify_steps=steps_i, emitted=max_new_tokens)
        self._note_compiles()

        tokens = buf[None, :prompt_len + max_new_tokens]
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=prefill_seconds,
                              decode_seconds=t2 - t1,
                              new_tokens=max_new_tokens,
                              decode_steps=max_new_tokens - 1,
                              verify_steps=steps_i, pad=pad)
