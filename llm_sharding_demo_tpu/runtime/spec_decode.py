"""Speculative decoding: prompt-lookup drafts + one-program greedy verify.

Latency optimization with **no reference counterpart** (the reference
forwards one token per two HTTP round-trips, reference server.py:169-181;
this module emits up to ``draft_len + 1`` tokens per forward). Greedy
speculative decoding is *provably token-exact*: a draft token is kept only
when it equals the model's own argmax at that position, so the emitted
stream is byte-identical to plain greedy decode — the parity test pins
this (tests/test_spec_decode.py). Sample mode is *distribution-exact* via
rejection sampling against the point-mass draft (see ``_loop_impl``),
reproducing the reference's temperature/top-k sampler distribution
(reference server.py:187-205) token for token — pinned by a pmf test.

Why it pays on TPU: single-stream decode is HBM-bandwidth-bound — every
step streams all weights to produce ONE token's worth of MXU work. A
verify step forwards ``K+1`` tokens through the same weights for the same
weight traffic, so each accepted draft is a nearly-free token. With
prompt-lookup drafting (Saxena's "prompt lookup decoding" /
assisted-generation n-gram variant) the draft model is the sequence
itself — no second network:

- **draft**: find the most recent previous occurrence of the last
  ``ngram`` tokens in the sequence so far; propose the ``draft_len``
  tokens that followed it (natural text and greedy GPT-2 output are both
  highly repetitive, so acceptance is high exactly when decode is long);
- **verify**: one cached forward of ``[t_last, d_1..d_K]`` at the current
  cache offset (ops.attention.cached_attention already supports S>1
  writes at a dynamic offset); accept the longest prefix where
  ``d_j == argmax(logits_{j-1})``, emit one bonus token from the first
  mismatch position;
- **rewind**: the KV written for rejected drafts is logically dropped by
  resetting ``KVCache.length`` (a traced scalar) — the stale slots sit
  beyond the valid length, are masked out of attention by ``kv_length``,
  and are physically overwritten by the next verify step's write at the
  rewound offset.

The whole generation after prefill is ONE compiled program: a
``lax.while_loop`` whose body is draft-match (vectorized n-gram scan, no
host work) + verify forward + buffer/cache bookkeeping. Single-stream
(batch=1) by design: per-row acceptance counts would need per-row cache
offsets, and speculation is a latency feature for exactly the
single-stream case (batched throughput is served by ``runtime.batcher``).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config, Params
from .engine import (DecodeEngine, GenerateResult, SamplingConfig,
                     prepare_generate, sampler_pmf, select_token)


class SpecDecodeEngine:
    """Speculative decode engine (single stream; greedy + sample modes).

    Composes a ``DecodeEngine`` for parameter preparation (dtype cast /
    int8 quantization / model-family dispatch) and its jitted prefill;
    replaces the token-by-token decode scan with the verify loop above.

    ``draft_len`` (K) is the speculation depth: each verify forward costs
    one (K+1)-token step and emits 1..K+1 tokens. ``ngram`` is the match
    width for prompt lookup (2 is the standard sweet spot: long enough to
    avoid noise matches, short enough to fire often).
    """

    def __init__(self, params: Params, config: GPT2Config, max_seq: int,
                 dtype=jnp.float32, draft_len: int = 6, ngram: int = 2,
                 prefill_chunk: Optional[int] = None):
        from ..models import is_window_independent
        if not is_window_independent(config):
            # Not an implementation gap — a semantic one: a (K+1)-token
            # verify forward must route identically to the plain engine's
            # single-token steps for the token-exactness guarantee to
            # hold (see models.is_window_independent).
            raise NotImplementedError(
                "speculative decoding requires window-independent token "
                "routing; MoE capacity-factor routing makes multi-token "
                "verify windows route differently than single-token "
                "decode steps — serve MoE with the plain engine")
        if draft_len < 1:
            raise ValueError("draft_len must be >= 1")
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        self.draft_len = draft_len
        self.ngram = ngram
        # The engine owns params/cache sizing (and chunked prefill); its
        # overflow guard also covers ours (we re-check with draft headroom
        # in generate()). decode_kernel is pinned to "xla" on BOTH sides:
        # the verify windows are multi-token (fused-XLA numerics), so a
        # kernel-decoding plain engine would break the token-exactness
        # contract between the spec stream and the plain fallback stream
        # on argmax near-ties.
        self._eng = DecodeEngine(params, config, max_seq, dtype=dtype,
                                 prefill_chunk=prefill_chunk,
                                 decode_kernel="xla")
        self.config = config
        self.max_seq = max_seq
        import threading
        self._stats_lock = threading.Lock()  # ThreadingHTTPServer callers
        self._requests = 0
        self._verifies = 0
        self._emitted = 0
        self._loop = jax.jit(self._loop_impl,
                             static_argnames=("max_new", "sampling"),
                             donate_argnums=(2,))

    @property
    def plain(self) -> DecodeEngine:
        """The wrapped plain engine (shared weights/compilations) — the
        serving layer routes ineligible requests here."""
        return self._eng

    def eligible(self, prompt_len: int, max_new_tokens: int) -> bool:
        """THE speculation-eligibility predicate: prompt long enough for
        an n-gram and ``draft_len`` slots of cache headroom for verify
        writes. The serving router and the prefix-cache front end both
        consult this (a request that fails it decodes plain)."""
        return (prompt_len >= self.ngram
                and prompt_len + max_new_tokens + self.draft_len
                <= self.max_seq)

    def stats(self) -> dict:
        """Cumulative speculation effectiveness (served at /healthz)."""
        with self._stats_lock:
            return {"requests": self._requests,
                    "verify_steps": self._verifies,
                    "emitted_tokens": self._emitted,
                    "draft_len": self.draft_len,
                    "tokens_per_verify": round(self._emitted
                                               / max(self._verifies, 1), 2)}

    # -- compiled verify loop ------------------------------------------------

    def _loop_impl(self, params, first_token, cache, buf, total, key, pad, *,
                   max_new: int, sampling: SamplingConfig):
        """(buf, total, cache) after prefill -> (buf, verify_steps).

        ``pad`` is ``None`` or a ``[1]`` int32 array: the left-pad prefix
        the chunk-aligned prefill placed in ``buf``/cache slots ``[0,
        pad)`` — masked as attention keys and excluded from the n-gram
        draft search (chunk padding must never become draft material).

        Invariant at loop entry: ``buf[:total]`` holds prompt + emitted
        tokens, ``cache.length == total - 1`` (the last emitted token has
        not been forwarded yet), ``emitted`` counts new tokens so far.

        Greedy acceptance compares drafts against the model argmax —
        token-exact by construction. Sample mode is *distribution-exact*
        rejection sampling against the point-mass draft: draft ``d_j`` is
        accepted with probability ``p_j(d_j)`` under the reference
        sampler's temperature/top-k pmf; the first rejection's bonus token
        is drawn from the residual ``p_j`` with ``d_j`` zeroed and
        renormalized (for a point-mass proposal the Leviathan residual
        ``max(0, p - q)/Z`` reduces to exactly that), and a fully-accepted
        window draws the bonus from ``p_K`` unmodified. Each emitted token
        is therefore distributed exactly as the plain sampler's — only the
        RNG consumption pattern differs, so seeded streams differ while
        the distribution does not (pinned by the pmf test)."""
        K, ngram = self.draft_len, self.ngram
        buflen = buf.shape[0]
        j_arr = jnp.arange(buflen, dtype=jnp.int32)

        low = jnp.int32(0) if pad is None else pad[0]

        def draft(buf, total, t_last):
            """Propose K tokens via most-recent n-gram match."""
            last = jax.lax.dynamic_slice(buf, (total - ngram,), (ngram,))
            match = jnp.ones((buflen,), dtype=bool)
            for t in range(ngram):
                match = match & (jnp.roll(buf, -t) == last[t])
            # exclude the current occurrence itself, anything past it,
            # and the left-pad prefix
            match = match & (j_arr < total - ngram) & (j_arr >= low)
            cand = jnp.where(match, j_arr, -1)
            best = cand.max()
            found = best >= 0
            start = jnp.where(found, best + ngram, 0)
            got = jax.lax.dynamic_slice(buf, (start,), (K,))
            # fallback: repeat the last token (catches token-loop output)
            return jnp.where(found, got, jnp.full((K,), t_last, jnp.int32))

        def accept_and_patch(logits, drafts, step_key):
            """[K+1, V] verify logits -> (n_accept, patch_tokens [K+1]).

            ``patch_tokens[j]`` is meaningful for ``j <= n_accept``:
            accepted drafts then the bonus token.
            """
            if sampling.mode == "greedy":
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                hits = (drafts == greedy[:K]).astype(jnp.int32)
                # greedy[j] is the token after x[j]; the bonus at the first
                # mismatch position is greedy itself, so patch == greedy
                return jnp.cumprod(hits).sum(), greedy
            # THE sampler distribution (engine.sampler_pmf: temperature +
            # top-k + optional nucleus) — shared with select_token so
            # acceptance probabilities and the plain sampler cannot drift
            probs, top_idx = sampler_pmf(logits, sampling)   # [K+1, k]
            k_acc, k_res = jax.random.split(step_key)
            in_topk = top_idx[:K] == drafts[:, None]         # [K, k]
            p_d = (probs[:K] * in_topk).sum(-1)              # [K]
            u = jax.random.uniform(k_acc, (K,))
            n_accept = jnp.cumprod((u < p_d).astype(jnp.int32)).sum()
            # bonus from row n_accept: the residual when a rejection
            # happened there, the plain pmf when every draft was accepted
            row_p, row_i = probs[n_accept], top_idx[n_accept]
            d_rej = drafts[jnp.minimum(n_accept, K - 1)]
            zero_d = (n_accept < K) & (row_i == d_rej)
            resid = jnp.where(zero_d, 0.0, row_p)
            choice = jax.random.categorical(k_res, jnp.log(resid))
            bonus = row_i[choice].astype(jnp.int32)
            dr_ext = jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)])
            return n_accept, jnp.where(jnp.arange(K + 1) < n_accept,
                                       dr_ext, bonus)

        def body(carry):
            buf, total, cache, emitted, steps, key = carry
            key, step_key = jax.random.split(key)
            t_last = buf[total - 1]
            drafts = draft(buf, total, t_last)
            x = jnp.concatenate([t_last[None], drafts])[None, :]  # [1, K+1]
            logits, cache = self._eng._forward_cached(params, x, cache, pad)
            n_accept, patch_tokens = accept_and_patch(logits[0], drafts,
                                                      step_key)
            n_emit = jnp.minimum(n_accept + 1, max_new - emitted)
            # splice the emitted tokens into buf at `total`
            old = jax.lax.dynamic_slice(buf, (total,), (K + 1,))
            patch = jnp.where(jnp.arange(K + 1) < n_emit, patch_tokens, old)
            buf = jax.lax.dynamic_update_slice(buf, patch, (total,))
            # rewind: forwarded-and-kept = t_last + the accepted prefix;
            # slots beyond are stale and masked by kv_length until the
            # next verify overwrites them at the rewound offset
            cache = cache._replace(
                length=(total - 1 + n_emit).astype(jnp.int32))
            return (buf, total + n_emit, cache, emitted + n_emit,
                    steps + 1, key)

        def cond(carry):
            return carry[3] < max_new

        first = first_token.reshape(()).astype(jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, first[None], (total,))
        carry = (buf, total + 1, cache, jnp.int32(1), jnp.int32(0), key)
        buf, _, cache, _, steps, _ = jax.lax.while_loop(cond, body, carry)
        return buf, steps, cache

    # -- public API ----------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None) -> GenerateResult:
        """Speculative generate: token-exact vs ``DecodeEngine.generate``
        in greedy mode, distribution-exact (rejection sampling, see
        ``_loop_impl``) in sample mode. Single-stream only (batches go
        through DecodeEngine / runtime.batcher).
        """
        ids, batch, prompt_len, key, pad = prepare_generate(
            prompt_ids, max_new_tokens, self.max_seq, sampling, key,
            allow_ragged=False)
        if batch != 1:
            raise ValueError("speculative decoding is single-stream "
                             "(batch=1); batched throughput goes through "
                             "DecodeEngine / runtime.batcher")
        if prompt_len < self.ngram:
            raise ValueError(
                f"prompt_len={prompt_len} shorter than ngram={self.ngram}")
        # Verify steps write up to draft_len tokens past the final length,
        # so the cache/position headroom check is stricter than the
        # engine's prompt+new <= max_seq guard.
        total_max = prompt_len + max_new_tokens + self.draft_len
        if total_max > self.max_seq:
            raise ValueError(
                f"prompt_len + max_new_tokens + draft_len = {total_max} "
                f"exceeds max_seq={self.max_seq}; verify writes need "
                "draft_len slots of headroom")

        # Chunk-align through the inner engine's shared helper; reserve
        # covers upcoming tokens AND the verify write headroom.
        ids, pad, prompt_len, chunk = self._eng._align_chunks(
            ids, pad, prompt_len, reserve=max_new_tokens + self.draft_len)

        ids_j = jnp.asarray(ids, dtype=jnp.int32)
        pad_j = jnp.asarray(pad) if pad.any() else None
        run_params = self._eng._run_params()

        t0 = time.perf_counter()
        prefill_key, loop_key = jax.random.split(key)
        if chunk:
            n_chunks = ids_j.shape[1] // chunk
            chunks = ids_j.reshape(1, n_chunks, chunk).transpose(1, 0, 2)
            last_logits, cache = self._eng._prefill_chunked(
                run_params, chunks,
                pad_j if pad_j is not None else jnp.zeros((1,), jnp.int32))
        else:
            last_logits, cache = self._eng._prefill(run_params, ids_j, pad_j)
        first = select_token(last_logits, sampling, prefill_key)
        first.block_until_ready()
        t1 = time.perf_counter()

        return self.run_loop(run_params, ids_j[0], first, cache, prompt_len,
                             loop_key, max_new_tokens, sampling,
                             prefill_seconds=t1 - t0,
                             pad=pad if pad.any() else None)

    def run_loop(self, run_params, prompt_row, first, cache,
                 prompt_len: int, loop_key, max_new_tokens: int,
                 sampling: SamplingConfig,
                 prefill_seconds: float = 0.0,
                 pad=None) -> GenerateResult:
        """Run the compiled verify loop off a prepared prefill state and
        assemble the result — shared by ``generate`` and the prefix-cache
        front end (runtime.prefix_cache), which produces (first, cache)
        its own way. Donates ``cache``; updates speculation stats.

        ``pad`` ([1] numpy, optional) is the single source of the
        left-pad prefix: the loop's device-side mask derives from it, and
        the result reports it for output stripping — one value, no way to
        desync the two uses."""
        pad_j = jnp.asarray(pad) if pad is not None and pad.any() else None
        t1 = time.perf_counter()
        buf = jnp.zeros((self.max_seq + self.draft_len + 1,), jnp.int32)
        buf = jax.lax.dynamic_update_slice(
            buf, jnp.asarray(prompt_row, dtype=jnp.int32), (0,))
        buf, steps, _ = self._loop(run_params, first[0], cache, buf,
                                   jnp.int32(prompt_len), loop_key, pad_j,
                                   max_new=max_new_tokens, sampling=sampling)
        buf = np.asarray(jax.block_until_ready(buf))
        t2 = time.perf_counter()

        steps_i = int(steps)
        with self._stats_lock:
            self._requests += 1
            self._verifies += steps_i
            self._emitted += max_new_tokens
        from ..utils.metrics import REGISTRY
        REGISTRY.inc("spec_verify_steps_total", value=steps_i)
        REGISTRY.inc("spec_emitted_tokens_total", value=max_new_tokens)

        tokens = buf[None, :prompt_len + max_new_tokens]
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=prefill_seconds,
                              decode_seconds=t2 - t1,
                              new_tokens=max_new_tokens,
                              decode_steps=max_new_tokens - 1,
                              verify_steps=steps_i, pad=pad)
