"""graftfleet in-process harness: a seeded multi-replica fleet.

The test/bench vehicle for the disaggregated topology: several REAL
``serving.app.create_app`` instances — one prefill replica, N decode
replicas — sharing ONE ``KVBlockPool`` process-locally (the same
pool-sharing contract a block-device service would provide across
processes), fronted by a real ``serving/router.py`` app. Everything
speaks the production dispatch path (``serving/http.py`` TestClient,
no sockets), so a graftload profile driven at the router exercises
exactly the hops, sheds, breakers, and block handoffs production
would.

Determinism: the model weights come from one pinned PRNG key, replica
names are stable, the router's ring is sha256-based, and graftload
schedules are pure functions of (seed, profile, k) — so a fleet run
under pinned GRAFTSCHED/GRAFTFAULT seeds replays its shed/affinity
accounting identically, and greedy outputs are byte-equal to the
single-replica path no matter which replica served them (the prefix
store is exact and every replica holds the same weights).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


def demo_model(max_seq: int = 128):
    """THE tiny pinned demo model every in-process harness serves —
    one definition (same geometry, same PRNGKey(0) weights) shared by
    ``build_fleet``, ``build_single``, and ``tools.graftload.
    build_demo_app``, so the fleet-vs-single byte-equality pins and the
    graftload bench target cannot drift apart."""
    import jax

    from ..models import gpt2

    cfg_model = gpt2.GPT2Config(vocab_size=256, n_positions=max_seq,
                                n_embd=32, n_layer=2, n_head=4)
    return cfg_model, gpt2.init_params(cfg_model, jax.random.PRNGKey(0))


@dataclasses.dataclass
class FleetHarness:
    """Everything a test/bench needs: the router's client plus every
    internal handle (pool conservation asserts, per-replica metric
    registries, recorder joins)."""

    client: object                    # TestClient at the router
    app: object                       # the router JSONApp
    topology: object                  # fleet.topology.FleetTopology
    pool: object                      # the SHARED KVBlockPool
    recorder: object                  # router FlightRecorder
    registry: object                  # router MetricsRegistry
    registries: Dict[str, object]     # replica name -> MetricsRegistry
    chunk: int = 64


def build_fleet(n_decode: int = 2, n_prefill: int = 1,
                max_seq: int = 128, max_batch: int = 1,
                kv_pool_blocks: int = 0, kv_block_size: int = 16,
                chunk: int = 16, prefix_cache: int = 8,
                recorder_capacity: int = 512,
                hop_policy=None) -> FleetHarness:
    """One shared-pool fleet: ``n_prefill`` prefill replicas (solo
    paged runners serving /prefill) and ``n_decode`` decode replicas,
    a router in front. ``max_batch=1`` (default) serves decode through
    solo ``PagedKVRunner``s — the ``prefill_shared`` ZERO-COPY
    adoption path, where a registered prefix's blocks land directly in
    the row's table; fleet concurrency comes from replica count, which
    is the disaggregation story. ``max_batch>1`` switches decode
    replicas to the pooled iteration scheduler (adoption then rides
    join-path admissions through the store; batch seeds prefill
    directly). ``kv_pool_blocks=0`` sizes the pool so every decode row
    plus growth headroom fits. ``chunk`` is the prefix store alignment
    width AND the router's affinity-key width — one value by
    construction, which is the drift the fleet pass guards wire
    deploys against."""
    from ..runtime.kv_pool import KVBlockPool
    from ..serving.app import create_app
    from ..serving.http import TestClient
    from ..serving.router import create_router_app
    from ..serving.tokenizer import ByteTokenizer
    from ..utils.config import ServingConfig
    from ..utils.metrics import MetricsRegistry
    from ..utils.tracing import FlightRecorder
    from .topology import FleetTopology, ReplicaHandle

    cfg_model, params = demo_model(max_seq)
    blocks_per_row = -(-max_seq // kv_block_size)
    if kv_pool_blocks <= 0:
        # every decode row at full depth + a couple of rows of growth/
        # registry headroom (watermark admission holds back the rest)
        kv_pool_blocks = (n_decode * max_batch + 2) * blocks_per_row
    heads = getattr(cfg_model, "n_kv_head", cfg_model.n_head)
    pool = KVBlockPool(cfg_model.n_layer, kv_pool_blocks, heads,
                       kv_block_size, cfg_model.head_dim, max_seq)
    tokenizer = ByteTokenizer()

    replicas: List[ReplicaHandle] = []
    registries: Dict[str, object] = {}

    def spawn(name: str, role: str, mb: int, mode: str) -> None:
        cfg = ServingConfig(
            model_id=f"graftfleet-{name}", shard_role="coordinator",
            max_seq=max_seq, boundaries=(1,), max_batch=mb,
            batch_mode=mode, batch_wait_ms=10.0,
            kv_pool_blocks=kv_pool_blocks, kv_block_size=kv_block_size,
            prefix_cache=prefix_cache, prefix_chunk=chunk,
            fleet_role=role)
        registry = MetricsRegistry()
        recorder = FlightRecorder(capacity=recorder_capacity)
        # ``replica=name`` labels the app's timeline events (grafttime
        # replica correlator), so a fleet run's unified stream shows
        # WHICH replica each request-scoped event happened on
        app = create_app(cfg, model=(cfg_model, params),
                         tokenizer=tokenizer, registry=registry,
                         recorder=recorder, kv_pool=pool, replica=name)
        registries[name] = registry
        replicas.append(ReplicaHandle(name=name, role=role,
                                      client=TestClient(app),
                                      recorder=recorder, app=app))

    for i in range(n_prefill):
        spawn(f"prefill{i}", "prefill", 1, "admission")
    for i in range(n_decode):
        spawn(f"decode{i}", "decode", max_batch,
              "iter" if max_batch > 1 else "admission")

    topology = FleetTopology(replicas)
    router_registry = MetricsRegistry()
    router_recorder = FlightRecorder(capacity=recorder_capacity)
    router_app = create_router_app(topology, tokenizer, chunk=chunk,
                                   registry=router_registry,
                                   recorder=router_recorder,
                                   hop_policy=hop_policy)
    return FleetHarness(client=TestClient(router_app), app=router_app,
                        topology=topology, pool=pool,
                        recorder=router_recorder,
                        registry=router_registry,
                        registries=registries, chunk=chunk)


def build_single(max_seq: int = 128, max_batch: int = 1,
                 kv_pool_blocks: int = 0, kv_block_size: int = 16,
                 chunk: int = 16, prefix_cache: int = 8,
                 recorder_capacity: int = 512):
    """The single-replica reference path the fleet is pinned
    byte-equal against: the SAME model weights and serving composition
    as one decode replica, its own pool, no router. Returns
    ``(client, recorder, registry)`` like ``tools.graftload.
    build_demo_app``."""
    from ..serving.app import create_app
    from ..serving.http import TestClient
    from ..serving.tokenizer import ByteTokenizer
    from ..utils.config import ServingConfig
    from ..utils.metrics import MetricsRegistry
    from ..utils.tracing import FlightRecorder

    cfg_model, params = demo_model(max_seq)
    if kv_pool_blocks <= 0:
        kv_pool_blocks = (max_batch + 2) * (-(-max_seq // kv_block_size))
    cfg = ServingConfig(
        model_id="graftfleet-single", shard_role="coordinator",
        max_seq=max_seq, boundaries=(1,),
        max_batch=max_batch,
        batch_mode="iter" if max_batch > 1 else "admission",
        batch_wait_ms=10.0, kv_pool_blocks=kv_pool_blocks,
        kv_block_size=kv_block_size, prefix_cache=prefix_cache,
        prefix_chunk=chunk)
    registry = MetricsRegistry()
    recorder = FlightRecorder(capacity=recorder_capacity)
    app = create_app(cfg, model=(cfg_model, params),
                     tokenizer=ByteTokenizer(), registry=registry,
                     recorder=recorder)
    return TestClient(app), recorder, registry
