"""graftfleet: disaggregated prefill/decode replica fleet (ROADMAP 2).

The dynamic half of the fleet subsystem — declared topology contracts
(:mod:`fleet.topology`), registry-keyed affinity placement
(:mod:`fleet.affinity`), and the seeded shared-pool harness
(:mod:`fleet.harness`) behind ``serving/router.py``. The static half
is the graftcheck fleet pass (``tools/graftcheck/fleet.py``).
"""

from .affinity import AFFINITY_KEY_SOURCE, HashRing, affinity_key
from .harness import FleetHarness, build_fleet, build_single
from .topology import (FLEET_ROLES, HANDOFF_POLICY, FleetTopology,
                       ReplicaHandle)

__all__ = [
    "AFFINITY_KEY_SOURCE", "FLEET_ROLES", "FleetHarness",
    "FleetTopology", "HANDOFF_POLICY", "HashRing", "ReplicaHandle",
    "affinity_key", "build_fleet", "build_single",
]
