"""graftfleet topology: declared replica roles + handoff contracts.

ROADMAP item 2: the paper's coordinator-plus-shards topology stops at
two toy stages; serving millions of users takes a data-parallel FLEET.
This module declares what that fleet IS — which roles exist, which
hops connect them, and what crosses each hop — as statically checkable
contracts (the registration-annotation idiom of ``FAULT_POLICY`` /
``GUARDED_STATE``), verified by ``tools/graftcheck/fleet.py``:

- **prefill replicas** run prompt prefills and FILL pool blocks: the
  chunk-aligned prefix states land in the shared pool's content-keyed
  prefix registry (``BlockAllocator.register_prefix`` via
  ``PrefixCachingEngine``), where entries hold their own block refs.
- **decode replicas** ADOPT those blocks zero-copy: a /generate whose
  prompt prefix is registered references the registry's physical
  blocks in its own table (``prefill_shared`` — the PR 5 machinery),
  CoW-copying only the partially-filled frontier block. Transfer
  across the prefill->decode boundary is BLOCK HANDOFF, never a
  tensor copy — Helix's placement-over-uniformity argument applied at
  the replica level (prefill and decode phases get their own
  replicas, not a uniform split of one).
- the **router** fronts the fleet (``serving/router.py``): routes by
  prefix-cache affinity over the registry's OWN content keys
  (``fleet/affinity.py``), sheds per-replica through the existing
  429/503 + Retry-After paths, and honors X-Deadline-Ms end-to-end
  across the extra hop.

The process-local form (``fleet/harness.py``: several ``create_app``
instances sharing ONE ``KVBlockPool``) is the seeded test/bench
vehicle; a multi-process fleet shares the pool through a block-device
service and keeps exactly these roles and hop contracts.

Declarations the fleet pass reads (dict literals on purpose — the
keys are statically visible, like ``PROFILES``/``SLO_POLICY``):

- ``FLEET_ROLES``: every role a replica may carry. A role literal in
  fleet code outside this registry is a finding, and a registered role
  nothing references is stale.
- ``HANDOFF_POLICY``: one entry per cross-replica hop,
  ``{hop: (from_role, to_role, what_crosses_and_who_owns_blocks)}``.
  Every ``_hop(...)`` dispatch in the router must name a declared
  entry; a declared entry with no live dispatch is stale.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

# THE role vocabulary (tools/graftcheck/fleet.py: fleet-role rule).
FLEET_ROLES = {
    "router": "fleet front end: affinity routing, per-replica "
              "breakers/shedding, deadline propagation, trace stitching",
    "prefill": "runs prompt prefills and fills shared pool blocks via "
               "the content-keyed prefix registry (/prefill)",
    "decode": "serves /generate, adopting registered prefix blocks "
              "zero-copy via prefill_shared (block handoff, no copy)",
}

# THE hop contract (tools/graftcheck/fleet.py: undeclared-replica-hop
# rule — every router dispatch names an entry here; fleet-role checks
# the endpoint roles). The third field documents block LIFETIME across
# the hop: what crosses the wire, and who holds which pool refs when.
HANDOFF_POLICY = {
    "router->prefill": (
        "router", "prefill",
        "only the prompt crosses; the prefill replica fills pool "
        "blocks and the registry takes its OWN refs (register_prefix) "
        "— the replica's transient caller refs are released before "
        "the response, so the hop hands off zero live leases"),
    "router->decode": (
        "router", "decode",
        "only the request crosses; the decode replica adopts "
        "registered blocks by reference (lookup_prefix caller refs in "
        "its own table, frontier block CoW'd before first write) and "
        "frees them at retirement — block handoff, never tensor copy"),
}


@dataclasses.dataclass
class ReplicaHandle:
    """One fleet member as the router sees it: a name (the breaker /
    metric / trace target label), a declared role, a client speaking
    the serving wire (``serving/http.py`` TestClient in-process; a
    requests-backed adapter over real sockets), and — in-process only
    — the replica's FlightRecorder so the router can stitch the
    replica's span tree into the request's own (/debug/requests shows
    one tree per request, hop included)."""

    name: str
    role: str
    client: object
    recorder: Optional[object] = None
    # the replica's own app handle (harness/test introspection only;
    # the router never touches it)
    app: Optional[object] = None


class FleetTopology:
    """Validated replica set: at least one decode replica, unique
    names, every role registered in ``FLEET_ROLES``."""

    def __init__(self, replicas: List[ReplicaHandle]):
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {sorted(names)}")
        for r in replicas:
            if r.role not in FLEET_ROLES:
                raise ValueError(
                    f"replica {r.name!r} carries unregistered role "
                    f"{r.role!r} (FLEET_ROLES: {sorted(FLEET_ROLES)})")
            if r.role == "router":
                raise ValueError(
                    "the router fronts the topology; it is not a "
                    "member replica")
        self.replicas = list(replicas)
        if not self.decode_replicas:
            raise ValueError("a fleet needs at least one decode replica "
                             "(who would serve /generate?)")

    @property
    def decode_replicas(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.role == "decode"]

    @property
    def prefill_replicas(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.role == "prefill"]

    def by_name(self, name: str) -> ReplicaHandle:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(name)

    def describe(self) -> dict:
        """The /healthz topology block: names by role."""
        return {
            "decode": [r.name for r in self.decode_replicas],
            "prefill": [r.name for r in self.prefill_replicas],
        }
