"""Prefix-cache affinity: route by the registry's OWN content keys.

The whole point of prefix-affinity routing is that the router's notion
of "same prefix" and the prefix registry's notion of "same prefix"
NEVER drift: if the router keyed on, say, a hash of the prompt string
while the registry keys on the int32 token bytes of chunk-aligned
prefixes, requests that share a cached prefix could scatter across
replicas (or worse, the router could co-locate requests the registry
considers distinct). So the affinity key here IS the registry's key —
``PrefixCachingEngine._key`` applied to the first chunk — and the
fleet pass (``tools/graftcheck/fleet.py``, ``affinity-key-drift``
rule) statically fails any independent re-derivation in this module.

Depth one chunk is deliberate: deeper keys fragment traffic that
shares a system prompt but diverges later (exactly the bursty-chat
shape), while the first chunk is the widest shared unit the registry
can cache at all (entries exist only at chunk multiples with at least
one token left to forward — prompts shorter than that have no
cacheable prefix and no affinity, and fall through to load placement).

The fallback placement is a CONSISTENT hash ring (sha256 points,
``VNODES`` virtual nodes per replica): adding or draining one decode
replica remaps only that replica's arc of keys instead of reshuffling
the whole fleet's prefix locality — the property a plain
``hash(key) % n`` loses on every scale event.
"""

from __future__ import annotations

import hashlib
from bisect import bisect_left
from typing import List, Optional, Sequence

import numpy as np

from ..runtime.prefix_cache import PrefixCachingEngine

# Where the affinity key comes from (tools/graftcheck/fleet.py,
# affinity-key-drift rule: this module must CALL the declared source
# and derive no content key of its own).
AFFINITY_KEY_SOURCE = \
    "llm_sharding_demo_tpu/runtime/prefix_cache.py:PrefixCachingEngine._key"

# virtual nodes per replica on the ring: enough to spread arcs evenly
# at fleet sizes this repo serves (2-16 replicas)
VNODES = 64


def affinity_key(prompt_ids: Sequence[int], chunk: int) -> Optional[bytes]:
    """The routing key for a tokenized prompt: the prefix registry's
    content key for the FIRST full chunk, or None when the prompt is
    too short to have any cacheable prefix (``m_max < 1`` — the same
    "leave >= 1 token to forward" floor the registry's lookup walks
    with). None routes by load, not affinity."""
    prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
    if (len(prompt) - 1) // chunk < 1:
        return None
    return PrefixCachingEngine._key(prompt, 1, chunk)


class HashRing:
    """Consistent-hash placement over replica names (sha256 points —
    process-independent, unlike builtin ``hash`` under hash
    randomization; the ring must agree across router restarts for
    affinity to mean anything)."""

    def __init__(self, names: Sequence[str], vnodes: int = VNODES):
        if not names:
            raise ValueError("HashRing needs at least one replica name")
        # immutable after construction (scale events build a new ring),
        # so reads need no lock
        pts = []
        for name in names:
            for i in range(vnodes):
                h = hashlib.sha256(f"{name}#{i}".encode()).digest()
                pts.append((int.from_bytes(h[:8], "big"), name))
        pts.sort()
        self._ring_points: List[int] = [p for p, _ in pts]
        self._ring_owners: List[str] = [o for _, o in pts]

    def pick(self, key: bytes) -> str:
        """The replica owning ``key``'s arc (first point clockwise)."""
        h = int.from_bytes(hashlib.sha256(key).digest()[:8], "big")
        i = bisect_left(self._ring_points, h)
        if i == len(self._ring_points):
            i = 0
        return self._ring_owners[i]
