"""Client for the /generate endpoint — the notebook client, as a module.

Equivalent of the reference notebook's ``generate_text`` cell
(notebook.ipynb cell a03cb3af: POST to the port-forwarded coordinator,
return the JSON on 200). Differences: errors raise instead of returning a
string that callers could mistake for model output (the reference's
mixed-return quirk, SURVEY.md §3.5), and the decode controls our server
adds (mode/seed/temperature/top_k/top_p/EOS stopping) are exposed.

Usage:
    from client import generate_text
    generate_text("Hi, ", max_new_tokens=20)
    generate_text("Hi, ", mode="greedy", base_url="http://host:30007")
    generate_text("Q: ...", top_p=0.9, stop_at_eos=True)
"""

from __future__ import annotations

from typing import Optional

import requests


def generate_text(prompt: str, max_new_tokens: int = 20,
                  base_url: str = "http://127.0.0.1:5000",
                  mode: str = "sample", seed: Optional[int] = None,
                  temperature: Optional[float] = None,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None,
                  stop_at_eos: bool = False,
                  eos_token_id: Optional[int] = None,
                  timeout: float = 120.0) -> str:
    """POST /generate and return the generated text.

    Omitted optional knobs are left out of the request body, so the
    server's defaults (the reference's temperature-0.6/top-k-40 sampler,
    no nucleus filter, no EOS stop) apply — keeping the default call
    wire-identical to the reference notebook's.
    """
    body = {"prompt": prompt, "max_new_tokens": max_new_tokens, "mode": mode}
    if seed is not None:
        body["seed"] = seed
    if temperature is not None:
        body["temperature"] = temperature
    if top_k is not None:
        body["top_k"] = top_k
    if top_p is not None:
        body["top_p"] = top_p
    if stop_at_eos:
        body["stop_at_eos"] = True
    if eos_token_id is not None:
        body["eos_token_id"] = eos_token_id
    resp = requests.post(f"{base_url}/generate", json=body, timeout=timeout)
    resp.raise_for_status()
    payload = resp.json()
    if "error" in payload:
        raise RuntimeError(f"server rejected request: {payload['error']}")
    return payload["generated"]


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("prompt")
    parser.add_argument("--max-new-tokens", type=int, default=20)
    parser.add_argument("--url", default="http://127.0.0.1:5000")
    parser.add_argument("--mode", default="sample",
                        choices=("sample", "greedy"))
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--temperature", type=float, default=None)
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--stop-at-eos", action="store_true")
    parser.add_argument("--eos-token-id", type=int, default=None)
    args = parser.parse_args()
    print(generate_text(args.prompt, args.max_new_tokens, args.url,
                        args.mode, args.seed, args.temperature, args.top_k,
                        args.top_p, args.stop_at_eos, args.eos_token_id))
