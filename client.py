"""Client for the /generate endpoint — the notebook client, as a module.

Equivalent of the reference notebook's ``generate_text`` cell
(notebook.ipynb cell a03cb3af: POST to the port-forwarded coordinator,
return the JSON on 200). Differences: errors raise instead of returning a
string that callers could mistake for model output (the reference's
mixed-return quirk, SURVEY.md §3.5), and the decode controls our server
adds (mode/seed) are exposed.

Usage:
    from client import generate_text
    generate_text("Hi, ", max_new_tokens=20)
    generate_text("Hi, ", mode="greedy", base_url="http://host:30007")
"""

from __future__ import annotations

from typing import Optional

import requests


def generate_text(prompt: str, max_new_tokens: int = 20,
                  base_url: str = "http://127.0.0.1:5000",
                  mode: str = "sample", seed: Optional[int] = None,
                  timeout: float = 120.0) -> str:
    body = {"prompt": prompt, "max_new_tokens": max_new_tokens, "mode": mode}
    if seed is not None:
        body["seed"] = seed
    resp = requests.post(f"{base_url}/generate", json=body, timeout=timeout)
    resp.raise_for_status()
    payload = resp.json()
    if "error" in payload:
        raise RuntimeError(f"server rejected request: {payload['error']}")
    return payload["generated"]


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("prompt")
    parser.add_argument("--max-new-tokens", type=int, default=20)
    parser.add_argument("--url", default="http://127.0.0.1:5000")
    parser.add_argument("--mode", default="sample",
                        choices=("sample", "greedy"))
    parser.add_argument("--seed", type=int, default=None)
    args = parser.parse_args()
    print(generate_text(args.prompt, args.max_new_tokens, args.url,
                        args.mode, args.seed))
