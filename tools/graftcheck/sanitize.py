"""graftsan Pass 1: donation-aliasing static analysis (compile-free).

PR 5 shipped a fix for a bug class the repo had no tooling to catch:
``np.asarray`` of a CPU jax array is a ZERO-COPY view of the device
buffer, and a later ``donate_argnums`` donation lets XLA rewrite that
memory in place under the view — the ``_SegOut`` token snapshots of
parked spec rows silently read rolled-over garbage. The hazard is
generic: every donation site in the runtime is a site where a host view
taken earlier (or a re-read after the call) dereferences freed storage.
This module is the static half of graftsan (the dynamic half — the
``GRAFTSAN=1`` pool sanitizer — lives in ``runtime.kv_pool``): an AST
pass over the production tree that makes donation a DECLARED contract
and walks call sites for the aliasing shapes that violate it.

In-file declarations (the registration annotations, same idiom as
``JIT_ENTRY_POINTS`` / ``GRAFTCHECK_HOT_LOOPS``):

- ``DONATED_ARGS``: dict literal ``{holding_name: (argnum, ...)}`` —
  every ``donate_argnums`` jit site in a ``runtime/`` module must be
  declared here (name AND exact indices), and every declaration must
  match a live site. The declarations double as the analyzer's
  resolution map: a call whose trailing name matches a declared
  donating callable is known to consume those argument positions.
- ``POOL_MOVER_SCOPES``: tuple of function qualnames in which invoking
  a pool data mover (``pool.gather`` / ``pool.scatter`` /
  ``pool.scatter_row`` / ``pool.scatter_columns`` / ``pool.cow_copy``)
  is legal — the scopes that provably hold a live ``BlockAllocator``
  lease on every block id they move. A mover call outside a declared
  scope is a finding; the dynamic sanitizer enforces the same property
  at runtime per block id.

Rules (ids in brackets; suppressions ride the shared baseline):

- [undeclared-donation]  ``donate_argnums`` site in ``runtime/`` with
                         no matching ``DONATED_ARGS`` entry, an entry
                         whose indices disagree with the site, or a
                         stale declaration — mirror image of the
                         ``undeclared-jit`` rule.
- [donated-view]         a host view (``np.asarray`` / ``.view()`` /
                         ``jax.device_get`` / ``np.array(copy=False)``)
                         of a value that flows into a declared donated
                         argument without an owning copy. Covers the
                         historical ``_SegOut`` shape: a module-local
                         class whose ``__init__`` stores an argument
                         and later host-views it uncopied makes every
                         ``Cls(x)`` call a view of ``x``.
- [donated-reuse]        a donated buffer read again after the
                         donating call in the same scope (before any
                         rebinding) — the buffer no longer belongs to
                         the caller.
- [pool-lease]           pool mover invoked outside a declared
                         ``POOL_MOVER_SCOPES`` scope (or a stale scope
                         declaration).

The dataflow is deliberately scope-local and name-based (union-find
aliasing over plain assignments, per-function statement order, dotted
names treated as persistent state): precise enough to pin the shapes
that have actually bitten, conservative enough to stay quiet on the
production tree without suppressions.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding
from . import lint as L

SANITIZE_RULE_IDS = ("undeclared-donation", "donated-view",
                     "donated-reuse", "pool-lease")

# pool data movers (KVBlockPool's device-op surface) and the receiver
# names a consumer holds a pool under
_MOVER_NAMES = {"gather", "scatter", "scatter_row", "scatter_columns",
                "cow_copy"}
_POOL_RECEIVERS = {"pool", "_pool"}


# -- declaration / site extraction -------------------------------------------


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def declared_donations(mod: L.ModuleInfo,
                       ) -> Tuple[Optional[Dict[str, Tuple[int, ...]]], int]:
    """The module's ``DONATED_ARGS`` dict literal -> ({name: indices},
    decl line); (None, 0) when the module declares nothing."""
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "DONATED_ARGS":
                if not isinstance(stmt.value, ast.Dict):
                    return {}, stmt.lineno
                out: Dict[str, Tuple[int, ...]] = {}
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    idxs = _int_tuple(v)
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and idxs is not None):
                        out[k.value] = idxs
                return out, stmt.lineno
    return None, 0


def declared_pool_scopes(mod: L.ModuleInfo,
                         ) -> Tuple[Optional[Set[str]], int]:
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "POOL_MOVER_SCOPES":
                vals = L._string_tuple(stmt.value)
                return (vals if vals is not None else set()), stmt.lineno
    return None, 0


@dataclasses.dataclass
class DonationSite:
    line: int
    name: Optional[str]                 # holding attr/def name
    indices: Optional[Tuple[int, ...]]  # None: non-literal donate_argnums
    scope: str


def _parent_map(tree: ast.Module) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _donate_kw(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return kw.value
    return None


def _enclosing_scope(node: ast.AST, parents: Dict[int, ast.AST],
                     mod: L.ModuleInfo) -> str:
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return mod.qualname_of.get(cur, cur.name)
        cur = parents.get(id(cur))
    return "<module>"


def donation_sites(mod: L.ModuleInfo) -> List[DonationSite]:
    """Every ``jax.jit(..., donate_argnums=...)`` site (direct call or
    ``functools.partial(jax.jit, donate_argnums=...)`` decorator), with
    the holding name resolved through the nearest Assign target or
    decorated def — wrap- and comprehension-tolerant by construction."""
    parents = _parent_map(mod.tree)
    out: List[DonationSite] = []
    for node in ast.walk(mod.tree):
        call = L._jit_call(node)
        if call is None:
            continue
        kw = _donate_kw(call)
        if kw is None:
            continue
        # resolve the holding name: nearest enclosing Assign target, or
        # the def this call decorates
        name = None
        cur: ast.AST = call
        while True:
            parent = parents.get(id(cur))
            if parent is None:
                break
            if isinstance(parent, ast.Assign):
                tgt = parent.targets[0]
                if isinstance(tgt, ast.Attribute):
                    name = tgt.attr
                elif isinstance(tgt, ast.Name):
                    name = tgt.id
                break
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and cur in parent.decorator_list:
                name = parent.name
                break
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef, ast.Module)):
                break
            cur = parent
        out.append(DonationSite(
            line=call.lineno, name=name, indices=_int_tuple(kw),
            scope=_enclosing_scope(call, parents, mod)))
    return out


def rule_undeclared_donation(mod: L.ModuleInfo) -> List[Finding]:
    """runtime/ modules must declare every donation site in
    DONATED_ARGS (name + exact indices); modules that declare anywhere
    are held to the same consistency."""
    declared, decl_line = declared_donations(mod)
    sites = donation_sites(mod)
    enforce = "/runtime/" in "/" + mod.relpath or declared is not None
    if not enforce or (not sites and declared is None):
        return []
    declared = declared or {}
    out: List[Finding] = []
    site_names = set()
    for s in sites:
        if s.name is None:
            out.append(Finding(
                "undeclared-donation", mod.relpath, s.line, s.scope,
                "donate_argnums site not held by a nameable attribute — "
                "the donation-aliasing pass cannot resolve its callers; "
                "bind it and declare it in DONATED_ARGS"))
            continue
        site_names.add(s.name)
        if s.indices is None:
            out.append(Finding(
                "undeclared-donation", mod.relpath, s.line, s.scope,
                f"donation site {s.name!r} uses a non-literal "
                "donate_argnums — the analyzer (and the reader) cannot "
                "tell which buffers the call consumes"))
        elif s.name not in declared:
            out.append(Finding(
                "undeclared-donation", mod.relpath, s.line, s.scope,
                f"donation site {s.name!r} missing from this module's "
                "DONATED_ARGS declaration (the donation-aliasing pass "
                "resolves callers through declared names only)"))
        elif declared[s.name] != s.indices:
            out.append(Finding(
                "undeclared-donation", mod.relpath, s.line, s.scope,
                f"DONATED_ARGS declares {s.name!r} donating "
                f"{declared[s.name]} but the site donates {s.indices} — "
                "callers analyzed against the declaration would miss "
                "the real consumed buffers"))
    for name in sorted(set(declared) - site_names):
        out.append(Finding(
            "undeclared-donation", mod.relpath, decl_line or 1, "<module>",
            f"DONATED_ARGS declares {name!r} but no donate_argnums site "
            "binds it (stale declaration)"))
    return out


# -- pool mover lease scopes --------------------------------------------------


def _mover_calls(mod: L.ModuleInfo) -> List[Tuple[int, str, str]]:
    """(line, scope, 'recv.mover') for every pool-mover invocation:
    attribute call whose receiver's trailing name is a pool handle."""
    parents = _parent_map(mod.tree)
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _MOVER_NAMES):
            continue
        recv = f.value
        recv_name = None
        if isinstance(recv, ast.Name):
            recv_name = recv.id
        elif isinstance(recv, ast.Attribute):
            recv_name = recv.attr
        if recv_name not in _POOL_RECEIVERS:
            continue
        out.append((node.lineno,
                    _enclosing_scope(node, parents, mod),
                    f"{recv_name}.{f.attr}"))
    return out


def rule_pool_lease(mod: L.ModuleInfo) -> List[Finding]:
    declared, decl_line = declared_pool_scopes(mod)
    calls = _mover_calls(mod)
    if not calls and declared is None:
        return []
    declared = declared or set()
    out: List[Finding] = []
    hit: Set[str] = set()
    for line, scope, what in calls:
        if scope in declared:
            hit.add(scope)
        else:
            out.append(Finding(
                "pool-lease", mod.relpath, line, scope,
                f"pool mover {what}(...) invoked outside a declared "
                "POOL_MOVER_SCOPES lease scope — block ids moved here "
                "have no statically known live BlockAllocator lease "
                "(declare the scope, or route through one that is)"))
    for scope in sorted(declared - hit):
        out.append(Finding(
            "pool-lease", mod.relpath, decl_line or 1, "<module>",
            f"POOL_MOVER_SCOPES declares {scope!r} but it invokes no "
            "pool mover (stale declaration)"))
    return out


# -- donation dataflow (donated-view / donated-reuse) -------------------------


def _expr_key(node: ast.AST) -> Optional[str]:
    """Dotted-name key of an expression, peeling subscripts and
    value-preserving wrappers (``jax.block_until_ready``)."""
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr == "block_until_ready"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jax" and node.args):
                node = node.args[0]
                continue
            return None
        break
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
        while isinstance(node, ast.Subscript):
            node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _kw_true(call: ast.Call, name: str) -> Optional[bool]:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return None


def _view_call(node: ast.Call, sinks: Dict[str, Set[int]],
               ) -> List[Tuple[ast.AST, str]]:
    """(viewed-expr, kind) pairs when ``node`` takes an uncopied host
    view of an argument."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else None
        if f.attr == "asarray" and base in ("np", "numpy") and node.args:
            return [(node.args[0], "np.asarray")]
        if (f.attr == "array" and base in ("np", "numpy") and node.args
                and _kw_true(node, "copy") is False):
            return [(node.args[0], "np.array(copy=False)")]
        if f.attr == "device_get" and base == "jax" and node.args:
            return [(node.args[0], "jax.device_get")]
        if f.attr == "view" and not node.keywords and len(node.args) <= 1:
            return [(f.value, ".view()")]
    elif isinstance(f, ast.Name) and f.id in sinks:
        return [(node.args[i], f"{f.id}(...)")
                for i in sinks[f.id] if i < len(node.args)]
    return []


def view_sink_classes(mod: L.ModuleInfo) -> Dict[str, Set[int]]:
    """Module-local classes whose ``__init__`` stores a positional arg
    into an attribute some method later host-views WITHOUT an owning
    copy — constructing one is then a view of that argument (the
    ``_SegOut`` bug shape)."""
    out: Dict[str, Set[int]] = {}
    for cls in mod.tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            continue
        params = [a.arg for a in init.args.args[1:]]  # past self
        attr_of_param: Dict[str, int] = {}
        for stmt in init.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Attribute)
                    and isinstance(stmt.targets[0].value, ast.Name)
                    and stmt.targets[0].value.id == "self"
                    and isinstance(stmt.value, ast.Name)
                    and stmt.value.id in params):
                attr_of_param[stmt.targets[0].attr] = \
                    params.index(stmt.value.id)
        if not attr_of_param:
            continue
        viewed: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                for expr, _kind in _view_call(node, {}):
                    key = _expr_key(expr)
                    if key and key.startswith("self."):
                        viewed.add(key[len("self."):].split(".")[0])
        idxs = {i for a, i in attr_of_param.items() if a in viewed}
        if idxs:
            out[cls.name] = idxs
    return out


class _Union:
    def __init__(self):
        self.parent: Dict[str, str] = {}

    def find(self, k: str) -> str:
        p = self.parent.setdefault(k, k)
        while p != self.parent.setdefault(p, p):
            p = self.parent[p]
        self.parent[k] = p
        return p

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb

    def members(self, root: str) -> List[str]:
        return [k for k in self.parent if self.find(k) == self.find(root)]


class _FlowScope:
    """Per-function donation dataflow: statements in textual order,
    donation/view/load/kill events keyed by union-find alias roots."""

    def __init__(self, mod: L.ModuleInfo, qual: str,
                 donating: Dict[str, Set[int]], sinks: Dict[str, Set[int]],
                 own_declared: Optional[Dict[str, Tuple[int, ...]]] = None):
        self.mod = mod
        self.qual = qual
        self.donating = donating
        self.sinks = sinks
        self.own_declared = own_declared or {}
        self.alias = _Union()
        self.viewed_live: Dict[str, List[Tuple[int, str]]] = {}
        self.viewed_all: Dict[str, List[Tuple[int, str]]] = {}
        self.donated_live: Dict[str, Tuple[int, str]] = {}
        self.donated_all: Dict[str, Tuple[int, str]] = {}
        self.local_donating: Dict[str, Set[int]] = {}  # IfExp aliases
        self.findings: List[Finding] = []
        self._reported: Set[Tuple[str, int]] = set()

    # -- event extraction --

    def _donation_indices(self, call: ast.Call) -> Optional[Set[int]]:
        f = call.func
        name = None
        if isinstance(f, ast.Attribute):
            name = f.attr
        elif isinstance(f, ast.Name):
            name = f.id
        if name is None:
            return None
        if name in self.local_donating:
            return self.local_donating[name]
        idxs = self.donating.get(name)
        if idxs is None:
            return None
        # collision guard: a plain def in THIS module shadowing a
        # donating name declared elsewhere (e.g. a method that happens
        # to share the trailing name) is not the donating callable
        if name in L._suffix_index(self.mod) \
                and name not in self.own_declared:
            return None
        return idxs

    def _emit(self, rule: str, line: int, msg: str) -> None:
        key = (rule, line)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(Finding(rule, self.mod.relpath, line,
                                     self.qual, msg))

    # -- statement processing --

    def run(self, fn: ast.AST) -> List[Finding]:
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        self._walk_stmts(body)
        # persistent-state hazard, order-insensitive: a donated value
        # whose alias class contains dotted (attribute) state outlives
        # this invocation — the NEXT call's donation frees what this
        # call's view still references (the cross-segment _SegOut bug).
        for root, (dline, dkey) in self.donated_all.items():
            persistent = any("." in m for m in self.alias.members(root))
            if not persistent:
                continue
            for vline, kind in self.viewed_all.get(self.alias.find(root),
                                                  []):
                self._emit(
                    "donated-view", vline,
                    f"{kind} takes a zero-copy host view of a value "
                    f"aliased to persistent state that is donated in "
                    f"this scope ({dkey!r}, donated at line {dline}): a "
                    "later donating call rewrites the viewed memory in "
                    "place — take an owning copy (np.array(x, "
                    "copy=True) / x.copy())")
        return self.findings

    def _walk_stmts(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scopes
            self._process(stmt)
            for attr in ("body", "orelse", "finalbody"):
                self._walk_stmts(getattr(stmt, attr, []) or [])
            for h in getattr(stmt, "handlers", []) or []:
                self._walk_stmts(h.body)

    def _process(self, stmt: ast.stmt) -> None:
        views: List[Tuple[int, str, str]] = []      # (line, key, kind)
        donations: List[Tuple[int, str, str]] = []  # (line, key, repr)
        loads: List[Tuple[int, str]] = []
        copied: Set[int] = set()

        # IfExp donation alias: fn = self._a if c else self._b
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.IfExp)):
            idxs: Set[int] = set()
            for branch in (stmt.value.body, stmt.value.orelse):
                got = None
                if isinstance(branch, (ast.Attribute, ast.Name)):
                    trailing = (branch.attr if isinstance(
                        branch, ast.Attribute) else branch.id)
                    got = self.donating.get(trailing)
                if got:
                    idxs |= got
            if idxs:
                self.local_donating[stmt.targets[0].id] = idxs

        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # owning-copy wrappers neutralize the inner view
            if isinstance(f, ast.Attribute) and f.attr == "copy" \
                    and isinstance(f.value, ast.Call):
                copied.add(id(f.value))
            if (isinstance(f, ast.Attribute) and f.attr == "array"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")
                    and _kw_true(node, "copy") is not False):
                for a in node.args:
                    if isinstance(a, ast.Call):
                        copied.add(id(a))
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                if id(node) not in copied:
                    for expr, kind in _view_call(node, self.sinks):
                        key = _expr_key(expr)
                        if key:
                            views.append((node.lineno, key, kind))
                idxs = self._donation_indices(node)
                if idxs:
                    for i in sorted(idxs):
                        if i < len(node.args):
                            key = _expr_key(node.args[i])
                            if key:
                                donations.append(
                                    (node.lineno, key,
                                     ast.unparse(node.func)))
            elif (isinstance(node, (ast.Name, ast.Attribute))
                  and isinstance(getattr(node, "ctx", None), ast.Load)):
                key = _expr_key(node)
                if key:
                    loads.append((node.lineno, key))

        donation_keys = {k for _, k, _ in donations}
        # donated-reuse: loads of a still-donated buffer (the donating
        # statement's own argument read is not a re-read)
        for line, key in loads:
            root = self.alias.find(key)
            if root in self.donated_live and key not in donation_keys:
                dline, dkey = self.donated_live[root]
                self._emit(
                    "donated-reuse", line,
                    f"{key!r} read after being donated at line {dline} "
                    f"({dkey!r}): the buffer was consumed by XLA and no "
                    "longer belongs to this scope — rebind the call's "
                    "output or copy before donating")
        # views: of an already-donated buffer (reuse-class), else record
        for line, key, kind in views:
            root = self.alias.find(key)
            if root in self.donated_live:
                dline, dkey = self.donated_live[root]
                self._emit(
                    "donated-view", line,
                    f"{kind} takes a host view of {key!r} AFTER its "
                    f"donation at line {dline}: the view reads storage "
                    "XLA already reclaimed")
            else:
                self.viewed_live.setdefault(root, []).append((line, kind))
                self.viewed_all.setdefault(root, []).append((line, kind))
        # donations: flag live earlier views, then mark
        for line, key, call_repr in donations:
            root = self.alias.find(key)
            for vline, kind in self.viewed_live.get(root, []):
                self._emit(
                    "donated-view", vline,
                    f"{kind} takes a zero-copy host view of {key!r} "
                    f"which is then donated at line {line} "
                    f"({call_repr}): the donation rewrites the viewed "
                    "memory in place — take an owning copy "
                    "(np.array(x, copy=True) / x.copy())")
            self.donated_live[root] = (line, key)
            self.donated_all[root] = (line, key)

        # stores: alias unions, then kills
        stores: List[str] = []
        if isinstance(stmt, ast.Assign):
            vkey = _expr_key(stmt.value)
            for tgt in stmt.targets:
                tkey = _expr_key(tgt)
                if tkey:
                    if vkey:
                        self.alias.union(tkey, vkey)
                    stores.append(tkey)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for elt in tgt.elts:
                        ekey = _expr_key(elt)
                        if ekey:
                            stores.append(ekey)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            tkey = _expr_key(stmt.target)
            if tkey:
                stores.append(tkey)
        elif isinstance(stmt, ast.For):
            tkey = _expr_key(stmt.target)
            if tkey:
                stores.append(tkey)
        for key in stores:
            root = self.alias.find(key)
            self.donated_live.pop(root, None)
            self.viewed_live.pop(root, None)


def _donating_map(mods: Sequence[L.ModuleInfo]) -> Dict[str, Set[int]]:
    """Union of every module's DONATED_ARGS declarations: trailing
    callable name -> consumed positional indices."""
    out: Dict[str, Set[int]] = {}
    for mod in mods:
        declared, _ = declared_donations(mod)
        for name, idxs in (declared or {}).items():
            out.setdefault(name, set()).update(idxs)
    return out


def rule_donation_flow(mod: L.ModuleInfo,
                       donating: Dict[str, Set[int]],
                       ) -> Tuple[List[Finding], int]:
    """-> (findings, functions flowed). The module's own DONATED_ARGS
    is resolved once here and shared by every scope (the collision
    guard consults it per call)."""
    sinks = view_sink_classes(mod)
    own_declared, _ = declared_donations(mod)
    findings: List[Finding] = []
    for qual, fn in sorted(mod.functions.items()):
        findings.extend(
            _FlowScope(mod, qual, donating, sinks,
                       own_declared=own_declared).run(fn))
    return findings, len(mod.functions)


# -- driver -------------------------------------------------------------------


def run_sanitize(root: str, paths: Optional[List[str]] = None,
                 ) -> Tuple[List[Finding], int]:
    """The whole static pass over the production surface (the lint's
    source set). -> (findings, checks_run) where ``checks_run`` counts
    real analysis units — donation sites validated, mover calls
    checked, and functions dataflowed — so a vacuity guard on the count
    actually proves the rules saw the tree (a file-count proxy would
    pass even with declaration parsing silently broken)."""
    mods: List[L.ModuleInfo] = []
    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is not None:
            mods.append(mod)
    donating = _donating_map(mods)
    findings: List[Finding] = []
    checks = len(donating)           # resolvable donating callables
    for mod in mods:
        findings.extend(rule_undeclared_donation(mod))
        checks += len(donation_sites(mod))
        findings.extend(rule_pool_lease(mod))
        checks += len(_mover_calls(mod))
        flow, n_fns = rule_donation_flow(mod, donating)
        findings.extend(flow)
        checks += n_fns
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            checks)
