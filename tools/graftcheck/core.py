"""Finding model + baseline suppressions shared by both graftcheck passes.

A finding is keyed by ``(rule, path, scope)`` — NOT by line number, so a
baselined intentional keep survives unrelated edits to the file above it.
``scope`` is the enclosing function's qualname (``Class.method`` /
``func.<locals>.inner``) or ``<module>`` for module-level findings; the
semantic pass uses contract coordinates (``family/plan/stage``) instead.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

Key = Tuple[str, str, str]

# Suppressions age: every baseline justification must carry a
# machine-checked ``audited: PR<n>`` tag naming the PR that last
# re-verified it, and --strict fails entries older than the last
# AUDIT_WINDOW PRs (the prose "re-audited in ISSUE <n>" comments above
# existed from the start — this makes the ritual checkable).
AUDIT_WINDOW = 8
_AUDIT_RE = re.compile(r"audited:\s*PR(\d+)\b")
_PR_RE = re.compile(r"^PR (\d+):", re.MULTILINE)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # repo-relative, forward slashes
    line: int
    scope: str
    message: str

    @property
    def key(self) -> Key:
        return (self.rule, self.path, self.scope)

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "scope": self.scope, "message": self.message}


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def load_baseline(path: str = None) -> Dict[Key, str]:
    """Parse baseline lines: ``rule<ws>path::scope<ws>justification``.

    ``#`` starts a comment; blank lines are skipped. The justification is
    mandatory — a suppression nobody can explain is a bug with a permit.
    """
    path = path or default_baseline_path()
    out: Dict[Key, str] = {}
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as f:
        for n, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 2)
            if len(parts) < 3 or "::" not in parts[1]:
                raise ValueError(
                    f"{path}:{n}: malformed baseline line (want "
                    f"'rule path::scope justification'): {line!r}")
            rule, loc, why = parts
            fpath, _, scope = loc.rpartition("::")
            out[(rule, fpath, scope)] = why
    return out


def baseline_audits(path: str = None) -> Dict[Key, Optional[int]]:
    """Per-entry ``audited: PR<n>`` tag from each justification —
    ``None`` for entries that never got one. Same parse (and the same
    malformed-line ValueError) as :func:`load_baseline`."""
    out: Dict[Key, Optional[int]] = {}
    for key, why in load_baseline(path).items():
        m = _AUDIT_RE.search(why)
        out[key] = int(m.group(1)) if m else None
    return out


def current_pr(root: str = None) -> Optional[int]:
    """This checkout's PR number: one past the highest ``PR <n>:``
    entry in CHANGES.md (the append-only per-PR log). ``None`` when the
    log is absent or empty — audit staleness can't be judged then."""
    root = root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, "CHANGES.md")
    try:
        with open(path, encoding="utf-8") as f:
            nums = [int(m) for m in _PR_RE.findall(f.read())]
    except OSError:
        return None
    return max(nums) + 1 if nums else None


def stale_audits(baseline_path: str = None, root: str = None,
                 window: int = AUDIT_WINDOW) -> List[str]:
    """Baseline entries whose audit tag is missing or older than the
    last ``window`` PRs — one formatted row each (empty = every
    suppression was re-verified recently enough). --strict fails on
    any row; the relaxed default stays report-only."""
    cur = current_pr(root)
    if cur is None:
        return []
    out: List[str] = []
    for (rule, fpath, scope), pr in sorted(baseline_audits(
            baseline_path).items()):
        loc = f"{fpath}::{scope} [{rule}]"
        if pr is None:
            out.append(f"{loc}: no 'audited: PR<n>' tag — re-verify the "
                       f"suppression and tag it (current PR {cur})")
        elif pr <= cur - window:
            out.append(f"{loc}: audited PR{pr}, but the window is the "
                       f"last {window} PRs (current PR {cur}) — "
                       "re-verify and re-tag")
    return out


def split_findings(findings: Iterable[Finding],
                   baseline: Dict[Key, str],
                   ) -> Tuple[List[Finding], List[Finding], Set[Key]]:
    """-> (active, suppressed, stale_baseline_keys).

    A baseline entry suppresses EVERY finding in its (rule, path, scope)
    — intentional keeps usually come in small clusters (e.g. the several
    fetches of one documented sync point) and one justification covers
    the scope. Stale keys (baselined but nothing found) are reported so
    fixed findings get their suppression removed.
    """
    active: List[Finding] = []
    suppressed: List[Finding] = []
    hit: Set[Key] = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            hit.add(f.key)
        else:
            active.append(f)
    stale = set(baseline) - hit
    return active, suppressed, stale
