"""Finding model + baseline suppressions shared by both graftcheck passes.

A finding is keyed by ``(rule, path, scope)`` — NOT by line number, so a
baselined intentional keep survives unrelated edits to the file above it.
``scope`` is the enclosing function's qualname (``Class.method`` /
``func.<locals>.inner``) or ``<module>`` for module-level findings; the
semantic pass uses contract coordinates (``family/plan/stage``) instead.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, List, Set, Tuple

Key = Tuple[str, str, str]


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # repo-relative, forward slashes
    line: int
    scope: str
    message: str

    @property
    def key(self) -> Key:
        return (self.rule, self.path, self.scope)

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "scope": self.scope, "message": self.message}


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def load_baseline(path: str = None) -> Dict[Key, str]:
    """Parse baseline lines: ``rule<ws>path::scope<ws>justification``.

    ``#`` starts a comment; blank lines are skipped. The justification is
    mandatory — a suppression nobody can explain is a bug with a permit.
    """
    path = path or default_baseline_path()
    out: Dict[Key, str] = {}
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as f:
        for n, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 2)
            if len(parts) < 3 or "::" not in parts[1]:
                raise ValueError(
                    f"{path}:{n}: malformed baseline line (want "
                    f"'rule path::scope justification'): {line!r}")
            rule, loc, why = parts
            fpath, _, scope = loc.rpartition("::")
            out[(rule, fpath, scope)] = why
    return out


def split_findings(findings: Iterable[Finding],
                   baseline: Dict[Key, str],
                   ) -> Tuple[List[Finding], List[Finding], Set[Key]]:
    """-> (active, suppressed, stale_baseline_keys).

    A baseline entry suppresses EVERY finding in its (rule, path, scope)
    — intentional keeps usually come in small clusters (e.g. the several
    fetches of one documented sync point) and one justification covers
    the scope. Stale keys (baselined but nothing found) are reported so
    fixed findings get their suppression removed.
    """
    active: List[Finding] = []
    suppressed: List[Finding] = []
    hit: Set[Key] = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            hit.add(f.key)
        else:
            active.append(f)
    stale = set(baseline) - hit
    return active, suppressed, stale
