"""The semantic pass's contract registry: families x plans x schedules.

Tiny CPU stand-in configs — the semantic pass runs everything through
``jax.eval_shape``/``jax.make_jaxpr``, so only shapes matter and tracing
a 4-layer / 8-wide model covers the same contract code paths as the
real checkpoints. Mesh axes are validated against
``jax.sharding.AbstractMesh`` stand-ins: no devices, no placement, no
compile.

Adding a family or plan here puts it under every check in
``semantic.run_semantic`` (stage contracts, pspec validity, padded
stacking round-trip, ring-permutation bijection).
"""

from __future__ import annotations

from typing import Dict, List, Tuple


def families() -> Dict[str, tuple]:
    """name -> (family module, tiny config). Stand-ins keep every
    divisibility property of the real configs (head_dim, kv grouping)
    at trace-instant sizes."""
    from llm_sharding_demo_tpu.models import gpt2, llama, moe
    return {
        "gpt2-tiny": (gpt2, gpt2.GPT2Config(
            vocab_size=96, n_positions=64, n_embd=8, n_layer=4, n_head=2)),
        "llama-tiny": (llama, llama.LlamaConfig(
            vocab_size=96, n_positions=64, n_embd=8, n_layer=4, n_head=2,
            n_kv_head=1, intermediate_size=16)),
        "moe-tiny": (moe, moe.MoEConfig(
            vocab_size=96, n_positions=64, n_embd=8, n_layer=2, n_head=2,
            n_experts=4, expert_top_k=2)),
    }


# partition plans per n_layer=4 stageable family: interior boundaries.
# Balanced 1/2/4-stage plans plus the uneven plans (padded stacking).
STAGE_PLANS: Tuple[Tuple[str, tuple], ...] = (
    ("1-stage", ()),
    ("2-stage", (2,)),
    ("4-stage", (1, 2, 3)),
    ("uneven-1+3", (1,)),
    ("uneven-3+1", (3,)),
    ("uneven-1+2+1", (1, 3)),
)

# mesh stand-ins for the PartitionSpec checks (axis name -> size)
MESHES: Dict[str, Dict[str, int]] = {
    "tp2": {"tp": 2},
    "dp2-tp2": {"dp": 2, "tp": 2},
    "ep2-tp2": {"ep": 2, "tp": 2},
    "pp4": {"pp": 4},
}

# stage-axis sizes the ppermute ring is verified over
RING_SIZES: Tuple[int, ...] = (1, 2, 3, 4, 8)

# stage counts the overlap lint traces the real PipelinedDecoder step at
# (n_layer=4 stand-ins: 2 balanced-even, 4 one-block stages)
OVERLAP_RING_SIZES: Tuple[int, ...] = (2, 4)

# Paged KV-pool geometries (runtime.kv_pool / ops.paged_attention) the
# block-table contract family is verified over: (label, kwargs for
# semantic.check_paged_contracts). Covers GQA (n_kv_head < n_head
# analog: kv heads independent of table math), a non-power-of-two
# block count, and batch widths 1/2/4 — every shape class the
# gather/scatter/attend programs see in serving.
PAGED_GEOMETRIES: Tuple[Tuple[str, dict], ...] = (
    ("paged-tiny", dict(n_layer=2, num_blocks=8, n_kv_head=2,
                        block_size=8, head_dim=4, max_seq=32,
                        batches=(1, 2))),
    ("paged-gqa", dict(n_layer=3, num_blocks=13, n_kv_head=1,
                       block_size=16, head_dim=8, max_seq=64,
                       batches=(1, 4))),
)


def planner_families() -> Dict[str, tuple]:
    """name -> (family module, tiny config) rows ``plan`` mode resolves
    ``--model`` against. Same trace-instant philosophy as ``families()``
    but with planner-relevant structure: the llama stand-in keeps a
    GQA ratio whose head counts a 2-wide tp axis divides (the
    ``families()`` stand-in's n_kv_head=1 deliberately exercises the
    indivisible case instead), and the moe stand-in's expert count
    divides a 2-wide ep axis."""
    from llm_sharding_demo_tpu.models import llama
    fams = families()
    return {
        "gpt2-tiny": fams["gpt2-tiny"],
        "llama-gqa": (llama, llama.LlamaConfig(
            vocab_size=96, n_positions=64, n_embd=16, n_layer=4, n_head=4,
            n_kv_head=2, intermediate_size=32)),
        "moe-tiny": fams["moe-tiny"],
    }


def serving_workloads() -> List[tuple]:
    """(label, EngineDesc kwargs, workload) rows the CLI certifies —
    canonical shapes of the serving configs the runtime tests pin (the
    full equality-vs-observed-cache-size check drives REAL engines and
    lives in tests/test_graftcheck.py)."""
    from . import recompile as R
    greedy = R.greedy_sampling()
    return [
        ("solo-greedy", R.EngineDesc(max_seq=64),
         [R.GenerateCall(prompt_lens=(8,), max_new=4, sampling=greedy)]),
        ("batch2-greedy", R.EngineDesc(max_seq=64),
         [R.GenerateCall(prompt_lens=(8, 8), max_new=4, sampling=greedy)]),
        ("chunked-prefill", R.EngineDesc(max_seq=128, prefill_chunk=16),
         [R.GenerateCall(prompt_lens=(40,), max_new=8, sampling=greedy)]),
        ("long-decode-windows", R.EngineDesc(max_seq=1024),
         [R.GenerateCall(prompt_lens=(16,), max_new=700,
                         sampling=greedy)]),
    ]


def paged_workloads() -> List[tuple]:
    """(label, EngineDesc kwargs, PagedDesc, workload) rows for the
    paged-decode recompile bounds: the PagedKVRunner's program space is
    the engine's own prefill/decode keys PLUS the pool's
    gather/scatter keys (one per batch-width x table-width pair) —
    certified equal to observed cache sizes in tests/test_kv_pool.py."""
    from . import recompile as R
    greedy = R.greedy_sampling()
    return [
        ("paged-solo", R.EngineDesc(max_seq=64),
         R.PagedDesc(max_seq=64, block_size=8),
         [R.GenerateCall(prompt_lens=(8,), max_new=12, sampling=greedy)]),
        ("paged-batch2", R.EngineDesc(max_seq=64),
         R.PagedDesc(max_seq=64, block_size=8),
         [R.GenerateCall(prompt_lens=(8, 8), max_new=12,
                         sampling=greedy)]),
    ]
