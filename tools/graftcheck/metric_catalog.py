"""Metric-name catalog rule: every literal metric name at a
``REGISTRY.inc/observe/gauge`` call site must appear in
``utils.metrics.METRIC_CATALOG`` with the matching instrument kind.

A typo'd metric name doesn't fail — it silently forks a brand-new time
series that no dashboard is watching (the counter you meant to increment
stays flat). Formerly the standalone ``tools/check_metrics.py`` (PR 2);
now a graftcheck rule so there is ONE lint entry point — the old CLI
remains as a thin shim over this module.

Only literal string names are checked; call sites passing a variable
(e.g. ``timed(name)``'s forwarding ``reg.observe(name, ...)``) are the
helper's responsibility and are skipped by construction — the helper's
CALLERS pass literals, which the regex does catch. The scan is
whole-file (wrap-tolerant): a name literal pushed to a continuation line
by line-length wrapping is still checked.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

_KIND_OF_CALL = {"inc": "counter", "observe": "histogram", "gauge": "gauge"}

# REGISTRY.inc("name"...) / reg.gauge('name'...) / timed("name"...) — the
# receiver must LOOK like a metrics registry handle (REGISTRY/reg/
# registry) or the timed() span helper, so pytest fixtures etc. don't
# false-positive.
_CALL_RE = re.compile(
    r"\b(?:REGISTRY|reg|registry)\s*\)?\s*\.\s*(inc|observe|gauge)\s*\(\s*"
    r"[\"']([A-Za-z_:][A-Za-z0-9_:]*)[\"']")
_TIMED_RE = re.compile(r"\btimed\s*\(\s*[\"']([A-Za-z_:][A-Za-z0-9_:]*)[\"']")
# graftscope occupancy time-series points (utils/graftscope.py sample):
# each series is the trajectory behind a same-named /metrics gauge, so
# the name must be a catalog GAUGE — a typo here would fork a series no
# dashboard (and no /debug/profile reader) is watching
_SAMPLE_RE = re.compile(
    r"\bgraftscope\s*\.\s*sample\s*\(\s*[\"']([A-Za-z_:][A-Za-z0-9_:]*)[\"']")


def _iter_sources(root: str) -> List[str]:
    """Production call sites: the package tree + bench.py (tests mint
    local throwaway names on purpose — they are not scraped)."""
    out = []
    pkg = os.path.join(root, "llm_sharding_demo_tpu")
    for dirpath, _, files in os.walk(pkg):
        out.extend(os.path.join(dirpath, f)
                   for f in files if f.endswith(".py"))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def find_violations(paths: List[str], catalog=None,
                    retired=None) -> List[Tuple[str, int, str, str]]:
    """(path, line_no, name, problem) for every call-site metric name
    missing from the catalog, used with the wrong instrument kind, or
    REVIVING a retired name (``utils.metrics.RETIRED_METRICS``): a
    replaced series must not silently fork back — dashboards migrated
    once, and a revived name would read as a fresh, unwatched series."""
    if catalog is None:
        from llm_sharding_demo_tpu.utils.metrics import METRIC_CATALOG
        catalog = METRIC_CATALOG
    if retired is None:
        from llm_sharding_demo_tpu.utils.metrics import RETIRED_METRICS
        retired = RETIRED_METRICS
    bad = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            text = f.read()

        def lineno(pos: int) -> int:
            return text.count("\n", 0, pos) + 1

        # whole-file scan (the `\s*` spans newlines), so a name literal
        # pushed to a continuation line by line-length wrapping is still
        # checked — a per-line scan would silently skip exactly the
        # typo class this tool exists to catch
        for m in _CALL_RE.finditer(text):
            call, name = m.group(1), m.group(2)
            want = catalog.get(name)
            if name in retired:
                bad.append((path, lineno(m.start()), name,
                            f"retired metric; use {retired[name]}"))
            elif want is None:
                bad.append((path, lineno(m.start()), name,
                            "not in METRIC_CATALOG"))
            elif want != _KIND_OF_CALL[call]:
                bad.append((path, lineno(m.start()), name,
                            f"catalog says {want}, call site "
                            f"uses .{call}()"))
        for m in _TIMED_RE.finditer(text):
            name = m.group(1)
            want = catalog.get(name)
            if name in retired:
                bad.append((path, lineno(m.start()), name,
                            f"retired metric; use {retired[name]}"))
            elif want is None:
                bad.append((path, lineno(m.start()), name,
                            "not in METRIC_CATALOG"))
            elif want != "histogram":
                bad.append((path, lineno(m.start()), name,
                            f"catalog says {want}, timed() "
                            "records a histogram"))
        for m in _SAMPLE_RE.finditer(text):
            name = m.group(1)
            want = catalog.get(name)
            if name in retired:
                bad.append((path, lineno(m.start()), name,
                            f"retired metric; use {retired[name]}"))
            elif want is None:
                bad.append((path, lineno(m.start()), name,
                            "not in METRIC_CATALOG"))
            elif want != "gauge":
                bad.append((path, lineno(m.start()), name,
                            f"catalog says {want}, graftscope.sample() "
                            "records a gauge time series"))
    return sorted(bad)


def as_findings(root: str, catalog=None) -> list:
    """The graftcheck-rule adapter: violations as ``core.Finding``s
    (rule id ``metric-catalog``, scope ``<module>``)."""
    from .core import Finding
    out = []
    for path, line, name, problem in find_violations(_iter_sources(root),
                                                     catalog):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        out.append(Finding(rule="metric-catalog", path=rel, line=line,
                           scope="<module>",
                           message=f"metric {name!r}: {problem}"))
    # a retired name re-added to the catalog is a config error in its
    # own right, reported against the catalog module itself
    from llm_sharding_demo_tpu.utils.metrics import (METRIC_CATALOG,
                                                     RETIRED_METRICS)
    cat = METRIC_CATALOG if catalog is None else catalog
    for name in sorted(set(cat) & set(RETIRED_METRICS)):
        out.append(Finding(
            rule="metric-catalog",
            path="llm_sharding_demo_tpu/utils/metrics.py", line=1,
            scope="<module>",
            message=f"metric {name!r}: retired name re-added to "
                    f"METRIC_CATALOG; use {RETIRED_METRICS[name]}"))
    return out


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or
            [os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))])[0]
    # scoped path insert: the test suite calls main() in-process, and a
    # permanent sys.path[0] prepend would leak into every later test
    # (the same leak class the _mega_mosaic_smoke satellite fixed)
    sys.path.insert(0, root)
    try:
        bad = find_violations(_iter_sources(root))
    finally:
        try:
            sys.path.remove(root)
        except ValueError:
            pass
    for path, line, name, problem in bad:
        print(f"{path}:{line}: metric {name!r}: {problem}")
    if bad:
        print(f"{len(bad)} metric-catalog violation(s); add the name to "
              "utils/metrics.py METRIC_CATALOG or fix the call site")
        return 1
    print("metric catalog OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
