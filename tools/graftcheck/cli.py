"""graftcheck CLI: ``python -m tools.graftcheck [--json] [--lint-only]``.

Exit code 0 iff every finding from both passes is baselined. ``--json``
emits one machine-readable object (journaled by bench.py alongside the
perf matrix, so contract drift shows up in the perf trajectory too).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import load_baseline, split_findings


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run(root: str = None, lint_only: bool = False,
        baseline_path: str = None) -> dict:
    """Both passes -> one JSON-able payload. Import-light until called;
    the semantic pass imports jax (CPU stand-ins only)."""
    root = root or _repo_root()
    # scoped insert (the same leak-class hygiene as the check_metrics
    # shim): in-suite callers run() in-process, and a permanent prepend
    # would leak into every later test
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import lint
        findings = list(lint.run_lint(root))
        semantic_checks = 0
        bounds = {}
        if not lint_only:
            from . import recompile, registry, semantic
            sem, semantic_checks = semantic.run_semantic()
            findings.extend(sem)
            for label, desc, calls in registry.serving_workloads():
                for call in calls:
                    for problem in recompile.planner_invariants(desc, call):
                        from .core import Finding
                        findings.append(Finding(
                            "recompile-budget",
                            "llm_sharding_demo_tpu/runtime/engine.py", 1,
                            label, problem))
                        semantic_checks += 1
                bounds[label] = recompile.certify(desc, calls)
                semantic_checks += len(calls)
            for label, desc, paged, pcalls in registry.paged_workloads():
                bounds[label] = recompile.certify_paged(desc, paged,
                                                        pcalls)
                semantic_checks += len(pcalls)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass

    baseline = load_baseline(baseline_path)
    active, suppressed, stale = split_findings(findings, baseline)
    return {
        "ok": not active,
        "findings": [f.to_dict() for f in active],
        "suppressed": len(suppressed),
        "stale_baseline": sorted("::".join(k[1:]) + f" [{k[0]}]"
                                 for k in stale),
        "semantic_checks": semantic_checks,
        "recompile_bounds": bounds,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="compile-free contract verifier + TPU-footgun lints")
    ap.add_argument("--root", default=None, help="repo root (default: "
                    "the checkout containing this tool)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    ap.add_argument("--lint-only", action="store_true",
                    help="skip the semantic (jax-tracing) pass")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/graftcheck/"
                    "baseline.txt)")
    args = ap.parse_args(argv)

    # standalone runs stay off any real accelerator: the semantic pass
    # needs only abstract avals/meshes. In-suite callers import run()
    # directly and keep their own backend config.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    payload = run(root=args.root, lint_only=args.lint_only,
                  baseline_path=args.baseline)
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        for f in payload["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}"
                  f"  (scope: {f['scope']})")
        for s in payload["stale_baseline"]:
            print(f"stale baseline entry (fixed? delete the line): {s}")
        n = len(payload["findings"])
        print(f"graftcheck: {n} active finding(s), "
              f"{payload['suppressed']} baselined, "
              f"{payload['semantic_checks']} semantic checks"
              + ("" if args.lint_only else
                 f", recompile bounds for {len(payload['recompile_bounds'])}"
                 " workload(s)"))
        if payload["ok"]:
            print("graftcheck OK")
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
