"""graftcheck CLI.

Three modes, one module entry point:

- ``python -m tools.graftcheck [--json] [--lint-only] [--strict]`` —
  the verifier (exit 0 iff every finding from both passes is baselined;
  under ``--strict`` a STALE baseline entry — a suppression whose
  finding no longer exists — is also a failure, so dead suppressions
  cannot rot in CI).
- ``python -m tools.graftcheck plan --model M --mesh SPEC --traffic T``
  — the planner (tools/graftcheck/costmodel.py): gate every candidate
  serving config through the verifier, score the survivors
  compile-free, print the ranked table and the chosen config's env
  vars. ``--json`` emits the full payload (schema:
  docs/ARCHITECTURE.md "Planning").
- ``python -m tools.graftcheck scope [--json]`` — measured-vs-modeled
  attribution (tools/graftcheck/scope.py): replay canonical workloads
  on tiny real engines with device-true dispatch timing, join the
  graftscope rings against the recompile certifier's program keys
  (exact rows must join 1:1 — the exit code) and report the implied
  byte rate against the cost model's per-token prediction.

``--json`` payloads are journaled by bench.py alongside the perf matrix
(rows ``graftcheck_static_analysis`` and ``graftcheck_chosen_plan``),
so contract drift and plan drift land in the same trajectory as the
timings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import load_baseline, split_findings


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run(root: str = None, lint_only: bool = False,
        baseline_path: str = None, strict: bool = False) -> dict:
    """All passes (lint + graftsan sanitize + semantic) -> one JSON-able
    payload. Import-light until called;
    the semantic pass imports jax (CPU stand-ins only). ``strict``
    fails the run on stale baseline entries too (the in-suite driver
    runs strict so CI catches dead suppressions; the standalone default
    stays report-only)."""
    root = root or _repo_root()
    # scoped insert (the same leak-class hygiene as the check_metrics
    # shim): in-suite callers run() in-process, and a permanent prepend
    # would leak into every later test
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import faults, fleet, lint, locks, memory, numerics, \
            sanitize, scope, slo, timeline, watch
        findings = list(lint.run_lint(root))
        san, sanitize_checks = sanitize.run_sanitize(root)
        findings.extend(san)
        lk, locks_summary = locks.run_locks(root)
        findings.extend(lk)
        fl, faults_summary = faults.run_faults(root)
        findings.extend(fl)
        sc, scope_summary = scope.run_scope_static(root)
        findings.extend(sc)
        sl, slo_summary = slo.run_slo(root)
        findings.extend(sl)
        ft, fleet_summary = fleet.run_fleet(root)
        findings.extend(ft)
        wt, watch_summary = watch.run_watch(root)
        findings.extend(wt)
        tl, timeline_summary = timeline.run_timeline(root)
        findings.extend(tl)
        mm, memory_summary = memory.run_memory(root)
        findings.extend(mm)
        # the numerics pass's jaxpr half traces real entry points —
        # skip it under --lint-only (the AST half still runs jax-free)
        nm, numerics_summary = numerics.run_numerics(root,
                                                     trace=not lint_only)
        findings.extend(nm)
        semantic_checks = 0
        bounds = {}
        if not lint_only:
            from . import recompile, registry, semantic
            sem, semantic_checks = semantic.run_semantic()
            findings.extend(sem)
            for label, desc, calls in registry.serving_workloads():
                for call in calls:
                    for problem in recompile.planner_invariants(desc, call):
                        from .core import Finding
                        findings.append(Finding(
                            "recompile-budget",
                            "llm_sharding_demo_tpu/runtime/engine.py", 1,
                            label, problem))
                        semantic_checks += 1
                bounds[label] = recompile.certify(desc, calls)
                semantic_checks += len(calls)
            for label, desc, paged, pcalls in registry.paged_workloads():
                bounds[label] = recompile.certify_paged(desc, paged,
                                                        pcalls)
                semantic_checks += len(pcalls)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass

    baseline = load_baseline(baseline_path)
    active, suppressed, stale = split_findings(findings, baseline)
    return {
        # strict additionally fails on a VACUOUS locks pass (a lock-
        # constructing module with zero guarded regions means the
        # concurrency contract stopped seeing that module's locking)
        # and on a VACUOUS profiling contract (a runtime module with
        # jit entry points but zero graftscope-instrumented dispatch
        # sites — device-time attribution went blind there) and on a
        # VACUOUS fault contract (a module with blocking boundaries
        # none of which its FAULT_POLICY covers)
        # and on a VACUOUS slo contract (an SLO_POLICY matching no
        # registered workload profile — the goodput gate stopped
        # seeing traffic)
        # and on a VACUOUS fleet contract (topology declarations —
        # HANDOFF_POLICY / HOP_SCOPES / HANDOFF_SCOPES /
        # AFFINITY_KEY_SOURCE — matching nothing live)
        # and on a VACUOUS watch contract (PLAN_SIGNALS resolving to no
        # live emitted series, or a PLAN_SET no builder constructs —
        # the live re-planner went blind or uncertified)
        # and on a VACUOUS timeline contract (a TIMELINE_EVENTS
        # declaration none of whose kinds are emitted — a producer on
        # the unified causal stream went dark)
        # and on a VACUOUS numerics contract (a PRECISION_CONTRACT
        # whose entries resolve to zero live functions — the precision
        # discipline stopped seeing that module's low-precision paths)
        # and on a VACUOUS memory contract (a MEMORY_LEDGER none of
        # whose holdings are registered — the HBM ledger went dark for
        # that module's residency)
        "ok": (not active and not (strict and stale)
               and not (strict and locks_summary["vacuous"])
               and not (strict and scope_summary["vacuous"])
               and not (strict and faults_summary["vacuous"])
               and not (strict and slo_summary["vacuous"])
               and not (strict and fleet_summary["vacuous"])
               and not (strict and watch_summary["vacuous"])
               and not (strict and timeline_summary["vacuous"])
               and not (strict and numerics_summary["vacuous"])
               and not (strict and memory_summary["vacuous"])),
        "strict": strict,
        "findings": [f.to_dict() for f in active],
        "suppressed": len(suppressed),
        "stale_baseline": sorted("::".join(k[1:]) + f" [{k[0]}]"
                                 for k in stale),
        "semantic_checks": semantic_checks,
        "sanitize_checks": sanitize_checks,
        "locks_checks": locks_summary["locks_checks"],
        "locks_guarded_regions": locks_summary["guarded_regions"],
        "locks_vacuous": locks_summary["vacuous"],
        "fault_checks": faults_summary["fault_checks"],
        "fault_policies": faults_summary["fault_policies"],
        "fault_vacuous": faults_summary["vacuous"],
        "scope_checks": scope_summary["scope_checks"],
        "scope_profiled_regions": scope_summary["profiled_regions"],
        "scope_vacuous": scope_summary["vacuous"],
        "slo_checks": slo_summary["slo_checks"],
        "slo_policies": slo_summary["slo_policies"],
        "slo_vacuous": slo_summary["vacuous"],
        "fleet_checks": fleet_summary["fleet_checks"],
        "fleet_policies": fleet_summary["fleet_policies"],
        "fleet_vacuous": fleet_summary["vacuous"],
        "watch_checks": watch_summary["watch_checks"],
        "watch_signals": watch_summary["watch_signals"],
        "watch_vacuous": watch_summary["vacuous"],
        "timeline_checks": timeline_summary["timeline_checks"],
        "timeline_kinds": timeline_summary["timeline_kinds"],
        "timeline_vacuous": timeline_summary["vacuous"],
        "memory_checks": memory_summary["memory_checks"],
        "memory_ledgers": memory_summary["memory_ledgers"],
        "memory_vacuous": memory_summary["vacuous"],
        "numerics_checks": numerics_summary["numerics_checks"],
        "numerics_contracts": numerics_summary["numerics_contracts"],
        "numerics_vacuous": numerics_summary["vacuous"],
        "recompile_bounds": bounds,
    }


def _parse_mesh(spec: str) -> dict:
    """``"tp=2"`` / ``"ep=2,tp=2"`` -> {axis: size}; ``"1"`` (or empty)
    = single device, no mesh axes."""
    spec = (spec or "").strip()
    if spec in ("", "1", "none"):
        return {}
    axes = {}
    for part in spec.split(","):
        name, sep, size = part.partition("=")
        try:
            n = int(size)
        except ValueError:
            n = 0
        if not sep or n < 1:
            raise ValueError(
                f"bad mesh element {part!r}: want axis=size with size "
                ">= 1, e.g. tp=2")
        axes[name.strip()] = n
    return axes


def run_plan(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = args.root or _repo_root()
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import costmodel, registry
        fams = registry.planner_families()
        if args.model not in fams:
            print(f"unknown --model {args.model!r}; registered planner "
                  f"families: {sorted(fams)}", file=sys.stderr)
            return 2
        module, config = fams[args.model]
        try:
            mesh_axes = _parse_mesh(args.mesh)
            traffic = (costmodel.parse_traffic(args.traffic)
                       if args.traffic else None)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        ici_w = None
        if getattr(args, "calibrate_journal", None):
            # the measure->model loop's consumer: re-price every
            # candidate's ICI term with the journal's measured
            # ici_byte_weight_calibration row (costmodel.calibrate)
            try:
                with open(args.calibrate_journal, encoding="utf-8") as f:
                    ici_w = costmodel.calibrate(json.load(f))
            except (OSError, json.JSONDecodeError) as e:
                print(f"cannot read --calibrate-journal "
                      f"{args.calibrate_journal}: {e}", file=sys.stderr)
                return 2
            except costmodel.CalibrationError as e:
                # present-but-unparsable row: a typed refusal, not a
                # silent fall-back to the a-priori weight
                print(f"calibrate: {e}", file=sys.stderr)
                return 2
            if ici_w is None:
                print("calibrate: journal carries no usable "
                      "ici_byte_weight_calibration row (skipped "
                      "off-chip?); scoring with the a-priori weight",
                      file=sys.stderr)
        payload = costmodel.plan(
            module, config, mesh_axes, max_seq=args.max_seq,
            traffic=traffic, max_batch_cap=args.max_batch,
            kv_pool_blocks=args.kv_blocks, kv_block_size=args.kv_block_size,
            hbm_gb=args.hbm_gb, ici_byte_weight=ici_w)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass

    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0 if payload["chosen"] is not None else 1
    print(f"graftplan: {args.model} on mesh {payload['mesh'] or '1 device'}"
          f", traffic {args.traffic or 'default'}"
          + (f", ici_byte_weight {payload['ici_byte_weight']} (calibrated)"
             if ici_w is not None else ""))
    for i, row in enumerate(payload["plan"][:args.top]):
        mark = "*" if payload["chosen"] and \
            row["label"] == payload["chosen"]["label"] else " "
        if row["ok"]:
            print(f" {mark} {i + 1:2d}. {row['label']:<32} "
                  f"cost/token {row['cost_per_token']:>12} "
                  f"comm {row['comm_bytes_per_token']:>8} "
                  f"hbm {row['hbm_bytes_per_device']:>10} "
                  f"programs {row['program_total']}"
                  f"{'' if row['programs_exact'] else ' (bound)'}")
        else:
            why = (row["findings"][0]["message"] if row["findings"]
                   else row["note"])
            print(f"   --. {row['label']:<32} REJECTED: {why[:80]}")
    if payload["chosen"] is None:
        print("graftplan: no candidate survived the verifier")
        return 1
    print("chosen serving env:")
    for k, v in sorted(payload["chosen"]["serving_env"].items()):
        print(f"  {k}={v}")
    return 0


def run_scope_cmd(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = args.root or _repo_root()
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import scope
        return scope.main_scope(args)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "scope":
        ap = argparse.ArgumentParser(
            prog="python -m tools.graftcheck scope",
            description="measured-vs-modeled attribution: replay the "
                        "canonical workloads on tiny real engines with "
                        "device-true dispatch timing and join the "
                        "graftscope rings against the certifier's "
                        "program keys and the cost model's predictions")
        ap.add_argument("--root", default=None)
        ap.add_argument("--json", action="store_true")
        return run_scope_cmd(ap.parse_args(argv[1:]))
    if argv and argv[0] == "plan":
        ap = argparse.ArgumentParser(
            prog="python -m tools.graftcheck plan",
            description="compile-free cost model + auto-sharding planner")
        ap.add_argument("--model", default="gpt2-tiny",
                        help="planner family (registry.planner_families)")
        ap.add_argument("--mesh", default="1",
                        help="mesh axes, e.g. 'tp=2' / 'ep=2'; '1' = "
                        "single device")
        ap.add_argument("--traffic", default=None,
                        help="traffic mix 'prompt/new[xcount],...', e.g. "
                        "'16/32x8,64/16'")
        ap.add_argument("--max-seq", type=int, default=64)
        ap.add_argument("--max-batch", type=int, default=8,
                        help="largest batch width candidates may use")
        ap.add_argument("--kv-blocks", type=int, default=0,
                        help="paged-pool block count to consider (0: only "
                        "contiguous candidates)")
        ap.add_argument("--kv-block-size", type=int, default=16)
        ap.add_argument("--hbm-gb", type=float, default=16.0,
                        help="per-device HBM feasibility budget")
        ap.add_argument("--calibrate-journal", default=None,
                        help="bench journal (BENCH_full/BENCH_rNN.json) "
                        "whose ici_byte_weight_calibration row re-prices "
                        "the ICI term with this host's measured byte "
                        "weight (costmodel.calibrate)")
        ap.add_argument("--top", type=int, default=12,
                        help="table rows to print (text mode)")
        ap.add_argument("--root", default=None)
        ap.add_argument("--json", action="store_true")
        return run_plan(ap.parse_args(argv[1:]))

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="compile-free contract verifier + TPU-footgun lints "
                    "(see also: the 'plan' subcommand)")
    ap.add_argument("--root", default=None, help="repo root (default: "
                    "the checkout containing this tool)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    ap.add_argument("--lint-only", action="store_true",
                    help="skip the semantic (jax-tracing) pass")
    ap.add_argument("--strict", action="store_true",
                    help="fail on stale baseline entries too (dead "
                    "suppressions)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/graftcheck/"
                    "baseline.txt)")
    args = ap.parse_args(argv)

    # standalone runs stay off any real accelerator: the semantic pass
    # needs only abstract avals/meshes. In-suite callers import run()
    # directly and keep their own backend config.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    payload = run(root=args.root, lint_only=args.lint_only,
                  baseline_path=args.baseline, strict=args.strict)
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        for f in payload["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}"
                  f"  (scope: {f['scope']})")
        for s in payload["stale_baseline"]:
            print(f"stale baseline entry (fixed? delete the line): {s}"
                  + (" [FAIL under --strict]" if args.strict else ""))
        n = len(payload["findings"])
        print(f"graftcheck: {n} active finding(s), "
              f"{payload['suppressed']} baselined, "
              f"{payload['semantic_checks']} semantic checks, "
              f"{payload['sanitize_checks']} sanitize checks, "
              f"{payload['fault_checks']} fault checks, "
              f"{payload['scope_checks']} scope checks, "
              f"{payload['slo_checks']} slo checks, "
              f"{payload['fleet_checks']} fleet checks, "
              f"{payload['watch_checks']} watch checks, "
              f"{payload['timeline_checks']} timeline checks, "
              f"{payload['memory_checks']} memory checks, "
              f"{payload['numerics_checks']} numerics checks"
              + ("" if args.lint_only else
                 f", recompile bounds for {len(payload['recompile_bounds'])}"
                 " workload(s)"))
        if payload["ok"]:
            print("graftcheck OK")
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
