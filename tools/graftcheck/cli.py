"""graftcheck CLI.

Three modes, one module entry point:

- ``python -m tools.graftcheck [--json] [--lint-only] [--strict]`` —
  the verifier (exit 0 iff every finding from both passes is baselined;
  under ``--strict`` a STALE baseline entry — a suppression whose
  finding no longer exists — is also a failure, so dead suppressions
  cannot rot in CI).
- ``python -m tools.graftcheck plan --model M --mesh SPEC --traffic T``
  — the planner (tools/graftcheck/costmodel.py): gate every candidate
  serving config through the verifier, score the survivors
  compile-free, print the ranked table and the chosen config's env
  vars. ``--json`` emits the full payload (schema:
  docs/ARCHITECTURE.md "Planning").
- ``python -m tools.graftcheck scope [--json]`` — measured-vs-modeled
  attribution (tools/graftcheck/scope.py): replay canonical workloads
  on tiny real engines with device-true dispatch timing, join the
  graftscope rings against the recompile certifier's program keys
  (exact rows must join 1:1 — the exit code) and report the implied
  byte rate against the cost model's per-token prediction.

``--json`` payloads are journaled by bench.py alongside the perf matrix
(rows ``graftcheck_static_analysis`` and ``graftcheck_chosen_plan``),
so contract drift and plan drift land in the same trajectory as the
timings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .core import load_baseline, split_findings, stale_audits

# canonical pass ids, in run order, for --passes selection. "sem"
# covers the semantic contract checks AND the recompile certifier —
# they share the jax-tracing stage --lint-only gates off.
PASS_IDS = ("lint", "sanitize", "locks", "faults", "scope", "slo",
            "fleet", "watch", "timeline", "trend", "memory", "tier",
            "numerics", "placement", "sem")

# payload keys each pass owns, with the value a SKIPPED pass reports:
# every key is always present whatever --passes selected (the schema
# test pins the full set), so journal consumers never branch on which
# passes ran — ``passes_run`` says which numbers are live.
_PASS_DEFAULTS = {
    "lint": {},
    "sanitize": {"sanitize_checks": 0},
    "locks": {"locks_checks": 0, "locks_guarded_regions": {},
              "locks_vacuous": []},
    "faults": {"fault_checks": 0, "fault_policies": {},
               "fault_vacuous": []},
    "scope": {"scope_checks": 0, "scope_profiled_regions": {},
              "scope_vacuous": []},
    "slo": {"slo_checks": 0, "slo_policies": {}, "slo_vacuous": []},
    "fleet": {"fleet_checks": 0, "fleet_policies": {},
              "fleet_vacuous": []},
    "watch": {"watch_checks": 0, "watch_signals": {},
              "watch_vacuous": []},
    "timeline": {"timeline_checks": 0, "timeline_kinds": {},
                 "timeline_vacuous": []},
    "trend": {"trend_checks": 0, "trend_policies": {},
              "trend_vacuous": []},
    "memory": {"memory_checks": 0, "memory_ledgers": {},
               "memory_vacuous": []},
    "tier": {"tier_checks": 0, "tier_policies": {},
             "tier_vacuous": []},
    "numerics": {"numerics_checks": 0, "numerics_contracts": {},
                 "numerics_vacuous": []},
    "placement": {"placement_checks": 0, "placement_contracts": {},
                  "placement_vacuous": []},
    "sem": {"semantic_checks": 0, "recompile_bounds": {}},
}

# the vacuous flags strict conjoins over (each list is "modules where
# this contract family went blind"); a SKIPPED pass contributes its
# falsy default, but strict refuses subsets outright (below), so a
# strict pass can never go green by not looking
_VACUOUS_KEYS = ("locks_vacuous", "scope_vacuous", "fault_vacuous",
                 "slo_vacuous", "fleet_vacuous", "watch_vacuous",
                 "timeline_vacuous", "trend_vacuous",
                 "numerics_vacuous", "memory_vacuous", "tier_vacuous",
                 "placement_vacuous")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run(root: str = None, lint_only: bool = False,
        baseline_path: str = None, strict: bool = False,
        passes=None) -> dict:
    """All passes (lint + graftsan sanitize + ... + placement +
    semantic) -> one JSON-able payload. Import-light until called; the
    traced halves import jax (CPU stand-ins only). ``strict`` fails the
    run on stale baseline entries, stale audit tags, and any VACUOUS
    contract pass (see the ok comment below); the in-suite driver runs
    strict so CI catches all three, the standalone default stays
    report-only. ``passes`` selects a subset of :data:`PASS_IDS`
    (default: all); strict refuses subsets — a strict run that skipped
    a pass would report green without looking."""
    root = root or _repo_root()
    selected = tuple(passes) if passes is not None else PASS_IDS
    unknown = sorted(set(selected) - set(PASS_IDS))
    if unknown:
        raise ValueError(f"unknown pass id(s) {unknown}; known passes: "
                         f"{', '.join(PASS_IDS)}")
    if strict and set(selected) != set(PASS_IDS):
        missing = sorted(set(PASS_IDS) - set(selected))
        raise ValueError("--strict requires the full pass set; missing: "
                         f"{', '.join(missing)}")

    findings = []
    fragments = {}
    for name in PASS_IDS:
        fragments.update(_PASS_DEFAULTS[name])
    pass_seconds = {}
    passes_run = []

    # scoped insert (the same leak-class hygiene as the check_metrics
    # shim): in-suite callers run() in-process, and a permanent prepend
    # would leak into every later test
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import faults, fleet, lint, locks, memory, numerics, \
            placement, sanitize, scope, slo, tier, timeline, trend, \
            watch

        def _summary(runner, keymap, **kw):
            def thunk():
                fs, s = runner(root, **kw)
                return fs, {out: s[src] for out, src in keymap.items()}
            return thunk

        def _lint():
            return list(lint.run_lint(root)), {}

        def _sanitize():
            fs, n = sanitize.run_sanitize(root)
            return fs, {"sanitize_checks": n}

        def _sem():
            from . import recompile, registry, semantic
            from .core import Finding
            fs, checks = semantic.run_semantic()
            fs = list(fs)
            bounds = {}
            for label, desc, calls in registry.serving_workloads():
                for call in calls:
                    for problem in recompile.planner_invariants(desc,
                                                                call):
                        fs.append(Finding(
                            "recompile-budget",
                            "llm_sharding_demo_tpu/runtime/engine.py", 1,
                            label, problem))
                        checks += 1
                bounds[label] = recompile.certify(desc, calls)
                checks += len(calls)
            for label, desc, paged, pcalls in registry.paged_workloads():
                bounds[label] = recompile.certify_paged(desc, paged,
                                                        pcalls)
                checks += len(pcalls)
            return fs, {"semantic_checks": checks,
                        "recompile_bounds": bounds}

        table = {
            "lint": _lint,
            "sanitize": _sanitize,
            "locks": _summary(locks.run_locks, {
                "locks_checks": "locks_checks",
                "locks_guarded_regions": "guarded_regions",
                "locks_vacuous": "vacuous"}),
            "faults": _summary(faults.run_faults, {
                "fault_checks": "fault_checks",
                "fault_policies": "fault_policies",
                "fault_vacuous": "vacuous"}),
            "scope": _summary(scope.run_scope_static, {
                "scope_checks": "scope_checks",
                "scope_profiled_regions": "profiled_regions",
                "scope_vacuous": "vacuous"}),
            "slo": _summary(slo.run_slo, {
                "slo_checks": "slo_checks",
                "slo_policies": "slo_policies",
                "slo_vacuous": "vacuous"}),
            "fleet": _summary(fleet.run_fleet, {
                "fleet_checks": "fleet_checks",
                "fleet_policies": "fleet_policies",
                "fleet_vacuous": "vacuous"}),
            "watch": _summary(watch.run_watch, {
                "watch_checks": "watch_checks",
                "watch_signals": "watch_signals",
                "watch_vacuous": "vacuous"}),
            "timeline": _summary(timeline.run_timeline, {
                "timeline_checks": "timeline_checks",
                "timeline_kinds": "timeline_kinds",
                "timeline_vacuous": "vacuous"}),
            "trend": _summary(trend.run_trend, {
                "trend_checks": "trend_checks",
                "trend_policies": "trend_policies",
                "trend_vacuous": "vacuous"}),
            "memory": _summary(memory.run_memory, {
                "memory_checks": "memory_checks",
                "memory_ledgers": "memory_ledgers",
                "memory_vacuous": "vacuous"}),
            "tier": _summary(tier.run_tier, {
                "tier_checks": "tier_checks",
                "tier_policies": "tier_policies",
                "tier_vacuous": "vacuous"}),
            # the numerics/placement jaxpr halves trace real entry
            # points — skipped under --lint-only (the AST halves still
            # run jax-free)
            "numerics": _summary(numerics.run_numerics, {
                "numerics_checks": "numerics_checks",
                "numerics_contracts": "numerics_contracts",
                "numerics_vacuous": "vacuous"}, trace=not lint_only),
            "placement": _summary(placement.run_placement, {
                "placement_checks": "placement_checks",
                "placement_contracts": "placement_contracts",
                "placement_vacuous": "vacuous"}, trace=not lint_only),
            "sem": _sem,
        }
        for name in PASS_IDS:
            if name not in selected:
                continue
            if name == "sem" and lint_only:
                continue
            t0 = time.perf_counter()
            fs, frag = table[name]()
            pass_seconds[name] = round(time.perf_counter() - t0, 3)
            passes_run.append(name)
            findings.extend(fs)
            fragments.update(frag)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass

    baseline = load_baseline(baseline_path)
    active, suppressed, stale = split_findings(findings, baseline)
    audits = stale_audits(baseline_path, root)
    return {
        # strict additionally fails on a VACUOUS locks pass (a lock-
        # constructing module with zero guarded regions means the
        # concurrency contract stopped seeing that module's locking)
        # and on a VACUOUS profiling contract (a runtime module with
        # jit entry points but zero graftscope-instrumented dispatch
        # sites — device-time attribution went blind there) and on a
        # VACUOUS fault contract (a module with blocking boundaries
        # none of which its FAULT_POLICY covers)
        # and on a VACUOUS slo contract (an SLO_POLICY matching no
        # registered workload profile — the goodput gate stopped
        # seeing traffic)
        # and on a VACUOUS fleet contract (topology declarations —
        # HANDOFF_POLICY / HOP_SCOPES / HANDOFF_SCOPES /
        # AFFINITY_KEY_SOURCE — matching nothing live)
        # and on a VACUOUS watch contract (PLAN_SIGNALS resolving to no
        # live emitted series, or a PLAN_SET no builder constructs —
        # the live re-planner went blind or uncertified)
        # and on a VACUOUS timeline contract (a TIMELINE_EVENTS
        # declaration none of whose kinds are emitted — a producer on
        # the unified causal stream went dark)
        # and on a VACUOUS numerics contract (a PRECISION_CONTRACT
        # whose entries resolve to zero live functions — the precision
        # discipline stopped seeing that module's low-precision paths)
        # and on a VACUOUS memory contract (a MEMORY_LEDGER none of
        # whose holdings are registered — the HBM ledger went dark for
        # that module's residency)
        # and on a VACUOUS placement contract (a PLACEMENT_CONTRACT
        # none of whose holdings/entries resolve to anything live —
        # placement discipline stopped seeing that module's mesh)
        # and on STALE AUDIT TAGS (a baseline justification whose
        # 'audited: PR<n>' tag is missing or older than the last
        # core.AUDIT_WINDOW PRs — the re-audit ritual lapsed)
        "ok": (not active and not (strict and stale)
               and not (strict and audits)
               and not any(strict and fragments[k]
                           for k in _VACUOUS_KEYS)),
        "strict": strict,
        "findings": [f.to_dict() for f in active],
        "suppressed": len(suppressed),
        # per-row suppressed findings (with their baseline
        # justifications) ride along for the SARIF emitter, which marks
        # them as externally suppressed rather than dropping them
        "suppressed_findings": [
            {**f.to_dict(), "justification": baseline.get(f.key, "")}
            for f in suppressed],
        "stale_baseline": sorted("::".join(k[1:]) + f" [{k[0]}]"
                                 for k in stale),
        "stale_audits": audits,
        "passes_run": passes_run,
        "pass_seconds": pass_seconds,
        "semantic_checks": fragments["semantic_checks"],
        "sanitize_checks": fragments["sanitize_checks"],
        "locks_checks": fragments["locks_checks"],
        "locks_guarded_regions": fragments["locks_guarded_regions"],
        "locks_vacuous": fragments["locks_vacuous"],
        "fault_checks": fragments["fault_checks"],
        "fault_policies": fragments["fault_policies"],
        "fault_vacuous": fragments["fault_vacuous"],
        "scope_checks": fragments["scope_checks"],
        "scope_profiled_regions": fragments["scope_profiled_regions"],
        "scope_vacuous": fragments["scope_vacuous"],
        "slo_checks": fragments["slo_checks"],
        "slo_policies": fragments["slo_policies"],
        "slo_vacuous": fragments["slo_vacuous"],
        "fleet_checks": fragments["fleet_checks"],
        "fleet_policies": fragments["fleet_policies"],
        "fleet_vacuous": fragments["fleet_vacuous"],
        "watch_checks": fragments["watch_checks"],
        "watch_signals": fragments["watch_signals"],
        "watch_vacuous": fragments["watch_vacuous"],
        "timeline_checks": fragments["timeline_checks"],
        "timeline_kinds": fragments["timeline_kinds"],
        "timeline_vacuous": fragments["timeline_vacuous"],
        "trend_checks": fragments["trend_checks"],
        "trend_policies": fragments["trend_policies"],
        "trend_vacuous": fragments["trend_vacuous"],
        "memory_checks": fragments["memory_checks"],
        "memory_ledgers": fragments["memory_ledgers"],
        "memory_vacuous": fragments["memory_vacuous"],
        "tier_checks": fragments["tier_checks"],
        "tier_policies": fragments["tier_policies"],
        "tier_vacuous": fragments["tier_vacuous"],
        "numerics_checks": fragments["numerics_checks"],
        "numerics_contracts": fragments["numerics_contracts"],
        "numerics_vacuous": fragments["numerics_vacuous"],
        "placement_checks": fragments["placement_checks"],
        "placement_contracts": fragments["placement_contracts"],
        "placement_vacuous": fragments["placement_vacuous"],
        "recompile_bounds": fragments["recompile_bounds"],
    }


def _parse_mesh(spec: str) -> dict:
    """``"tp=2"`` / ``"ep=2,tp=2"`` -> {axis: size}; ``"1"`` (or empty)
    = single device, no mesh axes."""
    spec = (spec or "").strip()
    if spec in ("", "1", "none"):
        return {}
    axes = {}
    for part in spec.split(","):
        name, sep, size = part.partition("=")
        try:
            n = int(size)
        except ValueError:
            n = 0
        if not sep or n < 1:
            raise ValueError(
                f"bad mesh element {part!r}: want axis=size with size "
                ">= 1, e.g. tp=2")
        axes[name.strip()] = n
    return axes


def run_plan(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = args.root or _repo_root()
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import costmodel, registry
        fams = registry.planner_families()
        if args.model not in fams:
            print(f"unknown --model {args.model!r}; registered planner "
                  f"families: {sorted(fams)}", file=sys.stderr)
            return 2
        module, config = fams[args.model]
        try:
            mesh_axes = _parse_mesh(args.mesh)
            traffic = (costmodel.parse_traffic(args.traffic)
                       if args.traffic else None)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        ici_w = None
        if getattr(args, "calibrate_journal", None):
            # the measure->model loop's consumer: re-price every
            # candidate's ICI term with the journal's measured
            # ici_byte_weight_calibration row (costmodel.calibrate)
            try:
                with open(args.calibrate_journal, encoding="utf-8") as f:
                    ici_w = costmodel.calibrate(json.load(f))
            except (OSError, json.JSONDecodeError) as e:
                print(f"cannot read --calibrate-journal "
                      f"{args.calibrate_journal}: {e}", file=sys.stderr)
                return 2
            except costmodel.CalibrationError as e:
                # present-but-unparsable row: a typed refusal, not a
                # silent fall-back to the a-priori weight
                print(f"calibrate: {e}", file=sys.stderr)
                return 2
            if ici_w is None:
                print("calibrate: journal carries no usable "
                      "ici_byte_weight_calibration row (skipped "
                      "off-chip?); scoring with the a-priori weight",
                      file=sys.stderr)
        payload = costmodel.plan(
            module, config, mesh_axes, max_seq=args.max_seq,
            traffic=traffic, max_batch_cap=args.max_batch,
            kv_pool_blocks=args.kv_blocks, kv_block_size=args.kv_block_size,
            hbm_gb=args.hbm_gb, ici_byte_weight=ici_w)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass

    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0 if payload["chosen"] is not None else 1
    print(f"graftplan: {args.model} on mesh {payload['mesh'] or '1 device'}"
          f", traffic {args.traffic or 'default'}"
          + (f", ici_byte_weight {payload['ici_byte_weight']} (calibrated)"
             if ici_w is not None else ""))
    for i, row in enumerate(payload["plan"][:args.top]):
        mark = "*" if payload["chosen"] and \
            row["label"] == payload["chosen"]["label"] else " "
        if row["ok"]:
            print(f" {mark} {i + 1:2d}. {row['label']:<32} "
                  f"cost/token {row['cost_per_token']:>12} "
                  f"comm {row['comm_bytes_per_token']:>8} "
                  f"hbm {row['hbm_bytes_per_device']:>10} "
                  f"programs {row['program_total']}"
                  f"{'' if row['programs_exact'] else ' (bound)'}")
        else:
            why = (row["findings"][0]["message"] if row["findings"]
                   else row["note"])
            print(f"   --. {row['label']:<32} REJECTED: {why[:80]}")
    if payload["chosen"] is None:
        print("graftplan: no candidate survived the verifier")
        return 1
    print("chosen serving env:")
    for k, v in sorted(payload["chosen"]["serving_env"].items()):
        print(f"  {k}={v}")
    return 0


def run_scope_cmd(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = args.root or _repo_root()
    added = root not in sys.path
    if added:
        sys.path.insert(0, root)
    try:
        from . import scope
        return scope.main_scope(args)
    finally:
        if added:
            try:
                sys.path.remove(root)
            except ValueError:
                pass


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "scope":
        ap = argparse.ArgumentParser(
            prog="python -m tools.graftcheck scope",
            description="measured-vs-modeled attribution: replay the "
                        "canonical workloads on tiny real engines with "
                        "device-true dispatch timing and join the "
                        "graftscope rings against the certifier's "
                        "program keys and the cost model's predictions")
        ap.add_argument("--root", default=None)
        ap.add_argument("--json", action="store_true")
        return run_scope_cmd(ap.parse_args(argv[1:]))
    if argv and argv[0] == "plan":
        ap = argparse.ArgumentParser(
            prog="python -m tools.graftcheck plan",
            description="compile-free cost model + auto-sharding planner")
        ap.add_argument("--model", default="gpt2-tiny",
                        help="planner family (registry.planner_families)")
        ap.add_argument("--mesh", default="1",
                        help="mesh axes, e.g. 'tp=2' / 'ep=2'; '1' = "
                        "single device")
        ap.add_argument("--traffic", default=None,
                        help="traffic mix 'prompt/new[xcount],...', e.g. "
                        "'16/32x8,64/16'")
        ap.add_argument("--max-seq", type=int, default=64)
        ap.add_argument("--max-batch", type=int, default=8,
                        help="largest batch width candidates may use")
        ap.add_argument("--kv-blocks", type=int, default=0,
                        help="paged-pool block count to consider (0: only "
                        "contiguous candidates)")
        ap.add_argument("--kv-block-size", type=int, default=16)
        ap.add_argument("--hbm-gb", type=float, default=16.0,
                        help="per-device HBM feasibility budget")
        ap.add_argument("--calibrate-journal", default=None,
                        help="bench journal (BENCH_full/BENCH_rNN.json) "
                        "whose ici_byte_weight_calibration row re-prices "
                        "the ICI term with this host's measured byte "
                        "weight (costmodel.calibrate)")
        ap.add_argument("--top", type=int, default=12,
                        help="table rows to print (text mode)")
        ap.add_argument("--root", default=None)
        ap.add_argument("--json", action="store_true")
        return run_plan(ap.parse_args(argv[1:]))

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="compile-free contract verifier + TPU-footgun lints "
                    "(see also: the 'plan' subcommand)")
    ap.add_argument("--root", default=None, help="repo root (default: "
                    "the checkout containing this tool)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    ap.add_argument("--lint-only", action="store_true",
                    help="skip the semantic (jax-tracing) pass")
    ap.add_argument("--strict", action="store_true",
                    help="fail on stale baseline entries too (dead "
                    "suppressions)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/graftcheck/"
                    "baseline.txt)")
    ap.add_argument("--sarif", action="store_true",
                    help="emit a SARIF 2.1.0 document instead of text "
                    "(baseline-suppressed findings ride along marked "
                    "suppressed)")
    ap.add_argument("--passes", default=None,
                    help="comma list of passes to run (default: all): "
                    + ",".join(PASS_IDS) + " — --strict requires the "
                    "full set")
    args = ap.parse_args(argv)

    # standalone runs stay off any real accelerator: the semantic pass
    # needs only abstract avals/meshes. In-suite callers import run()
    # directly and keep their own backend config.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    passes = None
    if args.passes is not None:
        passes = tuple(p.strip() for p in args.passes.split(",")
                       if p.strip())
    try:
        payload = run(root=args.root, lint_only=args.lint_only,
                      baseline_path=args.baseline, strict=args.strict,
                      passes=passes)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.sarif:
        from .sarif import to_sarif
        print(json.dumps(to_sarif(payload), indent=2))
    elif args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        for f in payload["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}"
                  f"  (scope: {f['scope']})")
        for s in payload["stale_baseline"]:
            print(f"stale baseline entry (fixed? delete the line): {s}"
                  + (" [FAIL under --strict]" if args.strict else ""))
        for s in payload["stale_audits"]:
            print(f"stale audit tag: {s}"
                  + (" [FAIL under --strict]" if args.strict else ""))
        n = len(payload["findings"])
        print(f"graftcheck: {n} active finding(s), "
              f"{payload['suppressed']} baselined, "
              f"{payload['semantic_checks']} semantic checks, "
              f"{payload['sanitize_checks']} sanitize checks, "
              f"{payload['fault_checks']} fault checks, "
              f"{payload['scope_checks']} scope checks, "
              f"{payload['slo_checks']} slo checks, "
              f"{payload['fleet_checks']} fleet checks, "
              f"{payload['watch_checks']} watch checks, "
              f"{payload['timeline_checks']} timeline checks, "
              f"{payload['trend_checks']} trend checks, "
              f"{payload['memory_checks']} memory checks, "
              f"{payload['tier_checks']} tier checks, "
              f"{payload['numerics_checks']} numerics checks, "
              f"{payload['placement_checks']} placement checks"
              + ("" if args.lint_only else
                 f", recompile bounds for {len(payload['recompile_bounds'])}"
                 " workload(s)"))
        if payload["ok"]:
            print("graftcheck OK")
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
