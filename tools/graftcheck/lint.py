"""Pass 2: AST lint rules for TPU serving footguns.

Shared driver: every scanned module is parsed ONCE into a ``ModuleInfo``
(AST + resolved jit sites/targets + in-file declarations), and each rule
is a function ``rule(mod) -> [Finding]``. Being AST-based, every rule is
wrap-tolerant by construction — a call split across continuation lines
is one ``ast.Call`` either way.

In-file declarations the rules key on (the "registration annotations"
the analyzer needs — grep for them in ``runtime/``):

- ``JIT_ENTRY_POINTS``: tuple of attribute/function names holding the
  module's jitted callables. The ``undeclared-jit`` rule enforces that
  every ``jax.jit`` call site in a runtime module is declared (and no
  declaration is stale) — the recompile-budget certifier
  (``recompile.py``) enumerates exactly these, so an undeclared site
  would be a compiled-program population the budget silently misses.
- ``GRAFTCHECK_HOT_LOOPS``: qualnames of decode hot-loop scopes — the
  functions whose bodies sit between compiled decode dispatches. The
  ``host-sync`` rule flags device->host synchronization inside them.

Rules (ids in brackets):

- [undeclared-jit]   jax.jit site in runtime/ not in JIT_ENTRY_POINTS
                     (or a stale declaration).
- [host-sync]        ``.item()`` / ``float()``/``int()`` on non-literals
                     / ``np.asarray``/``np.array`` /
                     ``block_until_ready`` inside a declared hot loop.
- [jit-in-handler]   ``jax.jit`` invoked in per-request scope (inside
                     any function) in ``serving/`` — jit belongs in
                     construction scope; a per-request jit retraces and
                     recompiles on every call.
- [jit-closure]      implicitly captured closure state in a jitted
                     lambda/nested function: a free variable that is not
                     a parameter, module-level name, enclosing ``def``,
                     or ``self`` gets baked in silently at trace time
                     (explicit default-arg binding ``_x=x`` is the
                     sanctioned pattern and does not flag).
- [time-in-jit]      ``time.time()``/``perf_counter()``/``monotonic()``
                     inside a jit target — traced once, constant
                     forever after.
- [metrics-in-jit]   ``REGISTRY.inc/observe/gauge`` / ``tracing.record/
                     span`` / ``timed(...)`` inside a jit target —
                     silent no-ops per the PR 2 contextvar design (they
                     run at trace time, not per step).
- [metric-catalog]   the former tools/check_metrics.py (see
                     ``metric_catalog.py``).
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding

_HOST_SYNC_NP = {"asarray", "array"}
_TIME_CALLS = {"time", "perf_counter", "monotonic", "time_ns",
               "perf_counter_ns"}
_METRIC_RECEIVERS = {"REGISTRY", "reg", "registry"}
_METRIC_METHODS = {"inc", "observe", "gauge"}
_TRACING_CALLS = {"record", "span", "timed", "annotate_span"}


# -- module model ------------------------------------------------------------


@dataclasses.dataclass
class JitSite:
    line: int
    name: Optional[str]          # holding attr/def name, if resolvable
    target: Optional[ast.AST]    # the jitted FunctionDef/Lambda node, if
                                 # resolvable within this module
    enclosing: str               # qualname of the enclosing function or
                                 # "<module>"
    depth: int                   # 0 = module level
    profiled: bool = False       # wrapped in graftscope.instrument(...)
                                 # (the scope pass's dispatch timer)


@dataclasses.dataclass
class ModuleInfo:
    path: str
    relpath: str
    source: str
    tree: ast.Module
    qualname_of: Dict[ast.AST, str]
    functions: Dict[str, ast.AST]          # qualname -> def node
    module_names: Set[str]                 # names bound at module level
    jit_sites: List[JitSite]
    declared_entry_points: Set[str]
    declared_hot_loops: Set[str]
    declared_profiled: Set[str]            # PROFILED_SCOPES declaration
    entry_decl_line: int
    profiled_decl_line: int
    jit_target_quals: Set[str]             # qualnames of jitted defs


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` as an expression (Attribute) — the repo's only form."""
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call whose programs a jit cache will hold, if ``node`` is one:
    ``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jax_jit(node.func):
        return node
    if (isinstance(node.func, ast.Attribute) and node.func.attr == "partial"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "functools"
            and node.args and _is_jax_jit(node.args[0])):
        return node
    return None


def _instrument_call(node: ast.AST) -> Optional[ast.Call]:
    """The inner ``jax.jit`` call when ``node`` is a graftscope dispatch
    wrapper — ``graftscope.instrument(jax.jit(...), "scope", ...)`` (or
    bare ``instrument(...)``). The wrapper is transparent to the jit-site
    rules (the holding name still resolves through the Assign target)
    and marks the site ``profiled`` for the scope pass."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    named = ((isinstance(f, ast.Attribute) and f.attr == "instrument"
              and isinstance(f.value, ast.Name)
              and f.value.id == "graftscope")
             or (isinstance(f, ast.Name) and f.id == "instrument"))
    if not named or not node.args:
        return None
    return _jit_call(node.args[0])


def _string_tuple(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            vals.add(elt.value)
        return vals
    return None


class _Indexer(ast.NodeVisitor):
    """One walk building qualnames, declarations, and jit sites."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.stack: List[str] = []        # enclosing def/class names
        self.kind_stack: List[str] = []   # "class" | "def"

    # -- scopes --

    def _qual(self, name: str) -> str:
        parts = []
        for n, k in zip(self.stack, self.kind_stack):
            parts.append(n)
            if k == "def":
                parts.append("<locals>")
        if parts and parts[-1] == "<locals>":
            pass
        return ".".join(parts + [name]).replace(".<locals>.", ".<locals>.")

    def _enclosing_fn(self) -> str:
        for n, k in reversed(list(zip(self.stack, self.kind_stack))):
            if k == "def":
                # rebuild the def's qualname
                idx = len(self.stack) - 1 - self.stack[::-1].index(n)
                return self._join(self.stack[:idx], self.kind_stack[:idx], n)
        return "<module>"

    @staticmethod
    def _join(stack, kinds, name) -> str:
        parts = []
        for n, k in zip(stack, kinds):
            parts.append(n)
            if k == "def":
                parts.append("<locals>")
        return ".".join(parts + [name])

    def _fn_depth(self) -> int:
        return sum(1 for k in self.kind_stack if k == "def")

    # -- visitors --

    def visit_ClassDef(self, node: ast.ClassDef):
        if not self.stack:
            # a module-level class is as safe a lambda reference as the
            # module-level functions/imports already whitelisted
            self.mod.module_names.add(node.name)
        self._visit_scope(node, "class")

    def visit_FunctionDef(self, node):
        self._handle_def(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _handle_def(self, node):
        qual = self._join(self.stack, self.kind_stack, node.name)
        self.mod.qualname_of[node] = qual
        self.mod.functions[qual] = node
        if not self.stack:
            self.mod.module_names.add(node.name)
        # decorator form: @jax.jit / @functools.partial(jax.jit, ...)
        for dec in node.decorator_list:
            if _is_jax_jit(dec) or _jit_call(dec) is not None:
                dec._gc_seen = True
                self.mod.jit_sites.append(JitSite(
                    line=node.lineno, name=node.name, target=node,
                    enclosing=self._enclosing_fn(),
                    depth=self._fn_depth()))
                self.mod.jit_target_quals.add(qual)
        self._visit_scope(node, "def")

    def _visit_scope(self, node, kind: str):
        self.stack.append(node.name)
        self.kind_stack.append(kind)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.stack.pop()
        self.kind_stack.pop()

    def visit_Assign(self, node: ast.Assign):
        # declarations
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if not self.stack or self.kind_stack == ["class"] * len(
                        self.kind_stack):
                    if tgt.id == "JIT_ENTRY_POINTS":
                        vals = _string_tuple(node.value)
                        if vals is not None:
                            self.mod.declared_entry_points |= vals
                            self.mod.entry_decl_line = node.lineno
                    elif tgt.id == "GRAFTCHECK_HOT_LOOPS":
                        vals = _string_tuple(node.value)
                        if vals is not None:
                            self.mod.declared_hot_loops |= vals
                    elif tgt.id == "PROFILED_SCOPES":
                        vals = _string_tuple(node.value)
                        if vals is not None:
                            self.mod.declared_profiled |= vals
                            self.mod.profiled_decl_line = node.lineno
                if not self.stack:
                    self.mod.module_names.add(tgt.id)
        # jit assignment forms: ``self.X = jax.jit(f, ...)`` and
        # ``X = jax.jit(f, ...)``, optionally wrapped in the graftscope
        # dispatch timer: ``self.X = graftscope.instrument(jax.jit(...),
        # "mod.X", ...)`` — the wrapper is name-transparent and marks
        # the site profiled (scope pass).
        profiled = False
        call = _jit_call(node.value)
        if call is None:
            call = _instrument_call(node.value)
            profiled = call is not None
        if call is not None:
            call._gc_seen = True
            name = None
            tgt = node.targets[0]
            if isinstance(tgt, ast.Attribute):
                name = tgt.attr
            elif isinstance(tgt, ast.Name):
                name = tgt.id
            self.mod.jit_sites.append(JitSite(
                line=node.lineno, name=name,
                target=self._resolve_target(call),
                enclosing=self._enclosing_fn(), depth=self._fn_depth(),
                profiled=profiled))
        self.generic_visit(node)

    def visit_Import(self, node):
        if not self.stack:
            for a in node.names:
                self.mod.module_names.add(
                    (a.asname or a.name).split(".")[0])
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if not self.stack:
            for a in node.names:
                self.mod.module_names.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        # an instrument wrapper outside an Assign: still one jit site
        # (unnamed — the undeclared-jit rule flags it), never two
        inner = _instrument_call(node)
        if inner is not None and not getattr(inner, "_gc_seen", False):
            inner._gc_seen = True
            self.mod.jit_sites.append(JitSite(
                line=node.lineno, name=None,
                target=self._resolve_target(inner),
                enclosing=self._enclosing_fn(), depth=self._fn_depth(),
                profiled=True))
        # bare jit calls not captured by Assign/decorator (e.g.
        # ``return jax.jit(...)`` or a jit inside an expression)
        call = _jit_call(node)
        if call is not None and not getattr(node, "_gc_seen", False):
            self.mod.jit_sites.append(JitSite(
                line=node.lineno, name=None,
                target=self._resolve_target(call),
                enclosing=self._enclosing_fn(), depth=self._fn_depth()))
        self.generic_visit(node)

    def _resolve_target(self, call: ast.Call) -> Optional[ast.AST]:
        """The function node being jitted, when it is visible here:
        a direct Lambda, or a Name/`self.X` resolved later by qualname."""
        args = call.args
        if _is_jax_jit(call.func):
            fn = args[0] if args else None
        else:  # functools.partial(jax.jit, f, ...)
            fn = args[1] if len(args) > 1 else None
        return fn


def _dedupe_sites(sites: List[JitSite]) -> List[JitSite]:
    """Assign/decorator visitors and the Call visitor can both see one
    site; collapse by (line): prefer the named record."""
    by_line: Dict[int, JitSite] = {}
    for s in sites:
        prev = by_line.get(s.line)
        if prev is None or (prev.name is None and s.name is not None):
            by_line[s.line] = s
    return [by_line[k] for k in sorted(by_line)]


def index_module(path: str, root: str) -> Optional[ModuleInfo]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    mod = ModuleInfo(path=path,
                     relpath=os.path.relpath(path, root).replace(os.sep, "/"),
                     source=source, tree=tree, qualname_of={}, functions={},
                     module_names=set(), jit_sites=[],
                     declared_entry_points=set(), declared_hot_loops=set(),
                     declared_profiled=set(), entry_decl_line=0,
                     profiled_decl_line=0, jit_target_quals=set())
    _Indexer(mod).visit(tree)
    mod.jit_sites = _dedupe_sites(mod.jit_sites)
    return mod


# -- jitted-body resolution ---------------------------------------------------


def _jitted_function_nodes(mod: ModuleInfo) -> List[Tuple[str, ast.AST]]:
    """(qualname-or-label, def/lambda node) for every function this
    module jits and whose body is visible in the module: decorated defs,
    ``jax.jit(self.X_impl)`` methods, ``jax.jit(local_fn)`` defs, and
    direct lambdas."""
    out: List[Tuple[str, ast.AST]] = []
    seen: Set[int] = set()

    def add(label, node):
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            out.append((label, node))

    for qual in mod.jit_target_quals:
        add(qual, mod.functions.get(qual))
    for site in mod.jit_sites:
        t = site.target
        if isinstance(t, ast.Lambda):
            add(f"{site.enclosing}:<lambda@{t.lineno}>", t)
        elif isinstance(t, ast.Attribute) and t.attr in _suffix_index(mod):
            add(*_suffix_index(mod)[t.attr])
        elif isinstance(t, ast.Name):
            # a local or module-level def with this trailing name
            hit = _suffix_index(mod).get(t.id)
            if hit is not None:
                add(*hit)
    return out


def _suffix_index(mod: ModuleInfo) -> Dict[str, Tuple[str, ast.AST]]:
    idx = getattr(mod, "_gc_suffix_idx", None)
    if idx is None:
        idx = {}
        for qual, node in mod.functions.items():
            leaf = qual.rpartition(".")[2]
            idx.setdefault(leaf, (qual, node))
        mod._gc_suffix_idx = idx
    return idx


# -- rules -------------------------------------------------------------------


def rule_undeclared_jit(mod: ModuleInfo) -> List[Finding]:
    """runtime/ modules must declare every jit site in JIT_ENTRY_POINTS."""
    if "/runtime/" not in "/" + mod.relpath:
        return []
    out = []
    site_names = {s.name for s in mod.jit_sites if s.name is not None}
    for s in mod.jit_sites:
        if s.name is None:
            out.append(Finding(
                "undeclared-jit", mod.relpath, s.line, s.enclosing,
                "jax.jit call site not held by a nameable attribute — "
                "the recompile-budget certifier cannot enumerate it; "
                "bind it to an attribute and declare it in "
                "JIT_ENTRY_POINTS"))
        elif s.name not in mod.declared_entry_points:
            out.append(Finding(
                "undeclared-jit", mod.relpath, s.line, s.enclosing,
                f"jit site {s.name!r} missing from this module's "
                "JIT_ENTRY_POINTS declaration (the recompile-budget "
                "certifier enumerates declared entry points only)"))
    for name in sorted(mod.declared_entry_points - site_names):
        out.append(Finding(
            "undeclared-jit", mod.relpath, mod.entry_decl_line or 1,
            "<module>",
            f"JIT_ENTRY_POINTS declares {name!r} but no jax.jit site "
            "binds it (stale declaration)"))
    return out


def _call_repr(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else "..."
        return f"{base}.{f.attr}()"
    if isinstance(f, ast.Name):
        return f"{f.id}()"
    return "call"


def _host_sync_calls(fn_node: ast.AST) -> List[Tuple[int, str]]:
    hits = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                hits.append((node.lineno, ".item() host-syncs the value"))
            elif (f.attr in _HOST_SYNC_NP
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")):
                hits.append((node.lineno,
                             f"{_call_repr(node)} copies device->host"))
            elif f.attr == "block_until_ready":
                hits.append((node.lineno,
                             "block_until_ready() stalls the dispatch "
                             "pipeline"))
        elif (isinstance(f, ast.Name) and f.id in ("float", "int")
                and node.args
                and not isinstance(node.args[0], ast.Constant)):
            hits.append((node.lineno,
                         f"{f.id}() on a non-literal host-syncs a "
                         "device value"))
    return hits


def rule_host_sync(mod: ModuleInfo) -> List[Finding]:
    out = []
    for qual in sorted(mod.declared_hot_loops):
        fn = mod.functions.get(qual)
        if fn is None:
            out.append(Finding(
                "host-sync", mod.relpath, 1, "<module>",
                f"GRAFTCHECK_HOT_LOOPS names {qual!r} but no such "
                "function exists in this module (stale declaration)"))
            continue
        for line, msg in _host_sync_calls(fn):
            out.append(Finding("host-sync", mod.relpath, line, qual,
                               msg + " inside a decode hot loop"))
    return out


def rule_jit_in_handler(mod: ModuleInfo) -> List[Finding]:
    if "/serving/" not in "/" + mod.relpath:
        return []
    return [Finding(
        "jit-in-handler", mod.relpath, s.line, s.enclosing,
        "jax.jit invoked in per-request scope — every call retraces and "
        "recompiles; build jitted callables once at construction")
        for s in mod.jit_sites if s.depth >= 1]


def _lambda_free_names(lam: ast.Lambda, mod: ModuleInfo,
                       enclosing_defs: Set[str]) -> List[Tuple[int, str]]:
    params = {a.arg for a in (lam.args.args + lam.args.kwonlyargs
                              + lam.args.posonlyargs)}
    if lam.args.vararg:
        params.add(lam.args.vararg.arg)
    if lam.args.kwarg:
        params.add(lam.args.kwarg.arg)
    known = (params | mod.module_names | set(dir(builtins))
             | enclosing_defs | {"self", "cls"})
    hits, seen = [], set()
    for node in ast.walk(lam.body):
        if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and node.id not in known and node.id not in seen):
            seen.add(node.id)
            hits.append((node.lineno, node.id))
    return hits


def rule_jit_closure(mod: ModuleInfo) -> List[Finding]:
    """Implicit closure capture in jitted lambdas: a free variable is
    baked in at trace time; if it later changes (or is unhashable
    non-array state) the program silently disagrees with the source.
    Explicit default-arg binding (``_x=x``) is the sanctioned pattern."""
    out = []
    enclosing_defs = {q.rpartition(".")[2] for q in mod.functions}
    for site in mod.jit_sites:
        if not isinstance(site.target, ast.Lambda):
            continue
        for line, name in _lambda_free_names(site.target, mod,
                                             enclosing_defs):
            out.append(Finding(
                "jit-closure", mod.relpath, line, site.enclosing,
                f"jitted lambda implicitly captures {name!r} from the "
                "enclosing scope (baked in at trace time); bind it "
                f"explicitly with a default arg (_x={name})"))
    return out


def _jit_body_calls(mod: ModuleInfo, match) -> List[Tuple[str, int, str]]:
    hits = []
    for label, fn in _jitted_function_nodes(mod):
        body = fn.body if isinstance(fn, ast.Lambda) else fn
        for node in ast.walk(body):
            if isinstance(node, ast.Call):
                msg = match(node)
                if msg:
                    hits.append((label, node.lineno, msg))
    return hits


def rule_time_in_jit(mod: ModuleInfo) -> List[Finding]:
    def match(node: ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _TIME_CALLS
                and isinstance(f.value, ast.Name)
                and f.value.id in ("time", "_time")):
            return (f"time.{f.attr}() inside a jitted function runs at "
                    "trace time only — the compiled program reuses one "
                    "frozen value")
        return None

    return [Finding("time-in-jit", mod.relpath, line, label, msg)
            for label, line, msg in _jit_body_calls(mod, match)]


def rule_metrics_in_jit(mod: ModuleInfo) -> List[Finding]:
    def match(node: ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _METRIC_METHODS
                and isinstance(f.value, ast.Name)
                and f.value.id in _METRIC_RECEIVERS):
            return (f"{f.value.id}.{f.attr}(...) under jit records at "
                    "trace time only (silent no-op per step); move it "
                    "off the compiled path")
        if (isinstance(f, ast.Attribute) and f.attr in _TRACING_CALLS
                and isinstance(f.value, ast.Name)
                and f.value.id == "tracing"):
            return (f"tracing.{f.attr}(...) under jit records at trace "
                    "time only; spans belong outside compiled programs")
        if isinstance(f, ast.Name) and f.id == "timed":
            return ("timed(...) under jit measures tracing, not steps; "
                    "move it off the compiled path")
        return None

    return [Finding("metrics-in-jit", mod.relpath, line, label, msg)
            for label, line, msg in _jit_body_calls(mod, match)]


RULES = (rule_undeclared_jit, rule_host_sync, rule_jit_in_handler,
         rule_jit_closure, rule_time_in_jit, rule_metrics_in_jit)

RULE_IDS = ("undeclared-jit", "host-sync", "jit-in-handler", "jit-closure",
            "time-in-jit", "metrics-in-jit", "metric-catalog")


def iter_sources(root: str) -> List[str]:
    """Same production surface as the metric-catalog rule: the package
    tree + bench.py."""
    from .metric_catalog import _iter_sources
    return _iter_sources(root)


def run_lint(root: str, paths: Optional[List[str]] = None,
             with_metric_catalog: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for path in (paths if paths is not None else iter_sources(root)):
        mod = index_module(path, root)
        if mod is None:
            findings.append(Finding(
                "syntax", os.path.relpath(path, root).replace(os.sep, "/"),
                1, "<module>", "file does not parse"))
            continue
        for rule in RULES:
            findings.extend(rule(mod))
    if with_metric_catalog:
        from . import metric_catalog
        findings.extend(metric_catalog.as_findings(root))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
