"""graftcheck numerics pass: declared precision contracts (compile-free).

The static half of **graftnum** (``llm_sharding_demo_tpu/utils/
graftnum.py`` is the dynamic half — the same split as sanitize/locks/
faults/slo/fleet/watch/timeline). Every exact path in this repo is
pinned byte-for-byte; the approximate paths (weight-only int8, bf16
decode, quantized KV blocks) until now carried their precision
discipline as PROSE — "LN stats, softmax and logits stay f32" — that no
pass checked. This pass makes precision a DECLARED contract:

Every ops/, runtime/, and models/ module with low-precision arithmetic
declares ``PRECISION_CONTRACT`` beside ``JIT_ENTRY_POINTS``::

    PRECISION_CONTRACT = {
        "<entry point>": {
            "regime": "f32" | "bf16" | "int8" | "fp8" | "carried",
            "casts": ("f32", "bf16", "int8", "fp8", "carried", ...),
            "accumulate": "f32",          # required when low-precision
                                          # dots/reductions exist
            "exact": True | False,
            "oracle": "decode.int8",      # required when exact: False
        },
    }

``regime`` is the dtype regime of the entry's value stream at its
boundary (``carried`` = output follows the input's dtype); ``casts``
are the SANCTIONED cast boundaries (dtype tokens the body may convert
to; ``carried`` sanctions dynamic ``x.astype(other.dtype)`` casts and,
in traced jaxprs, converts back to an input operand's dtype);
``accumulate: "f32"`` declares the f32-accumulator discipline for
low-precision dots; ``exact: False`` routes the path to a declared
``graftnum.TOLERANCE_POLICY`` budget.

Two analysis halves feed four rules:

- **AST half** (always on): contract shape/vocabulary validation, the
  module-level low-precision trigger, and a cast scan over each
  contracted entry's body (``.astype`` / ``lax.convert_element_type``
  sites resolved to dtype tokens; integer index casts are control flow,
  not value precision, and are ignored).
- **Jaxpr half** (skipped under ``--lint-only``): the semantic-pass
  pattern — :func:`traced_entry_points` builds ``jax.make_jaxpr``
  programs of the REAL production entry points at representative
  low-precision avals and walks the equations: ``convert_element_type``
  destinations against the declared boundaries, ``dot_general``/
  accumulating reductions over sub-f32 operands against the declared
  f32-accumulator discipline, and output avals against the declared
  regime. Compile-free (tracing only), injectable for fixtures.

Rules (ids in brackets; suppressions ride the shared baseline):

- [undeclared-cast]      a low-precision ops/ or runtime/ module with
                         no PRECISION_CONTRACT, a malformed/stale
                         declaration, or a cast site (AST or traced
                         jaxpr) whose destination token is not a
                         declared boundary of its entry.
- [unstable-reduction]   a traced dot_general/reduce/cumsum over
                         bf16/f16/int8 avals without f32 accumulation
                         (``preferred_element_type`` or ≥f32 output) —
                         or with one but no declared ``accumulate:
                         "f32"`` — the rule that makes ops/quant.py's
                         prose checkable.
- [silent-downcast]      a traced entry whose output narrows below its
                         declared regime (or below the carried input
                         dtype) — an f32 value quietly leaving a jit
                         boundary as bf16 that nothing declared.
- [approx-without-oracle] an ``exact: False`` entry with no ``oracle``
                         mapping or one naming no TOLERANCE_POLICY
                         path; an ``exact: True`` entry CLAIMING an
                         oracle path (a byte-equality pin cannot claim
                         an approx-declared path); a TOLERANCE_POLICY
                         path no contract references (stale); or a
                         malformed policy (the slo-without-source-
                         metric shape).

``--strict`` additionally fails a VACUOUS pass (a PRECISION_CONTRACT
whose entries resolve to zero live functions); ``cli.run --json``
carries ``numerics_checks`` / ``numerics_contracts`` /
``numerics_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign

NUMERICS_RULE_IDS = ("undeclared-cast", "unstable-reduction",
                     "silent-downcast", "approx-without-oracle")

# The dtype-regime vocabulary (graftnum.REGIMES mirrors this — tests
# pin the two stay equal, like the slo pass's SLO_METRICS).
NUM_REGIMES = ("f32", "bf16", "int8", "fp8")
# contract regimes add "carried": output dtype follows the input's
CONTRACT_REGIMES = NUM_REGIMES + ("carried",)
# sanctioned-cast vocabulary: value-precision dtype tokens + "carried"
CAST_TOKENS = ("f32", "bf16", "f16", "f64", "int8", "fp8", "carried")
# the two oracle metrics every TOLERANCE_POLICY path must declare
ORACLE_METRICS = ("logit_mse", "top1_agreement")

GRAFTNUM_RELPATH = "llm_sharding_demo_tpu/utils/graftnum.py"

# dtype-name -> token; names outside this map and outside _IGNORED are
# still value dtypes (conservative: an unknown float spelling flags)
_DTYPE_TOKENS = {
    "float32": "f32", "f32": "f32",
    "bfloat16": "bf16", "bf16": "bf16",
    "float16": "f16", "f16": "f16", "fp16": "f16", "half": "f16",
    "float64": "f64", "f64": "f64", "double": "f64",
    "int8": "int8",
    # fp8 spellings map to one token the traced rules can width-check.
    # The regime is DECLARED (quantized KV block storage, ops/kv_quant.py)
    # with its TOLERANCE_POLICY path "kv.fp8" — fp8 casts are sanctionable
    # wherever a contract lists the token, same as int8.
    "float8_e4m3fn": "fp8", "float8_e5m2": "fp8", "fp8": "fp8",
}
# integer/bool/index casts are control flow, not value precision
_IGNORED_DTYPES = {
    "int32", "int64", "int16", "uint8", "uint16", "uint32", "uint64",
    "bool", "bool_", "i32", "i64",
}
_TOKEN_WIDTH = {"f64": 64, "f32": 32, "bf16": 16, "f16": 16, "int8": 8,
                "fp8": 8}

_LOW_PRECISION_NAMES = {"bfloat16", "float16", "int8", "float8_e4m3fn",
                        "float8_e5m2"}


# -- contract model ----------------------------------------------------------


class _Entry:
    """One parsed PRECISION_CONTRACT entry."""

    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.regime: Optional[str] = None
        self.casts: Set[str] = set()
        self.accumulate: Optional[str] = None
        self.exact: Optional[bool] = None
        self.oracle: Optional[str] = None


def _str_dict_items(node: ast.AST) -> Optional[List[Tuple[str, ast.AST]]]:
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        out.append((k.value, v))
    return out


def _const(node: ast.AST):
    return node.value if isinstance(node, ast.Constant) else None


def _str_seq(node: ast.AST) -> Optional[List[str]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.append(e.value)
    return out


def _parse_contract(mod: L.ModuleInfo,
                    findings: List[Finding]) -> Optional[List[_Entry]]:
    """PRECISION_CONTRACT -> validated entries; malformed declarations
    land as undeclared-cast findings (the contract itself is the first
    thing held to the vocabulary)."""
    stmt = _module_assign(mod, "PRECISION_CONTRACT")
    if stmt is None:
        return None
    line = stmt.lineno
    items = _str_dict_items(stmt.value)
    if items is None:
        findings.append(Finding(
            "undeclared-cast", mod.relpath, line, "<module>",
            "PRECISION_CONTRACT must be a dict literal keyed by entry-"
            "point name (the numerics pass reads it statically)"))
        return []
    entries: List[_Entry] = []
    for name, spec in items:
        e = _Entry(name, line)
        fields = _str_dict_items(spec)
        if fields is None:
            findings.append(Finding(
                "undeclared-cast", mod.relpath, line, name,
                f"entry {name!r}: the contract value must be a dict "
                "literal {regime, casts, exact[, accumulate, oracle]}"))
            continue
        fmap = dict(fields)
        regime = _const(fmap.get("regime"))
        if regime not in CONTRACT_REGIMES:
            findings.append(Finding(
                "undeclared-cast", mod.relpath, line, name,
                f"entry {name!r}: regime {regime!r} is outside the "
                f"declared vocabulary {CONTRACT_REGIMES}"))
            continue
        e.regime = regime
        casts = _str_seq(fmap.get("casts", ast.Tuple(elts=[], ctx=None)))
        if casts is None or any(c not in CAST_TOKENS for c in casts):
            findings.append(Finding(
                "undeclared-cast", mod.relpath, line, name,
                f"entry {name!r}: casts must be a tuple/list literal of "
                f"tokens from {CAST_TOKENS} (the sanctioned cast "
                "boundaries)"))
            continue
        e.casts = set(casts)
        exact = _const(fmap.get("exact"))
        if not isinstance(exact, bool):
            findings.append(Finding(
                "undeclared-cast", mod.relpath, line, name,
                f"entry {name!r}: exact must be a True/False literal — "
                "byte-pinned or tolerance-gated, never unstated"))
            continue
        e.exact = exact
        if "accumulate" in fmap:
            acc = _const(fmap["accumulate"])
            if acc != "f32":
                findings.append(Finding(
                    "undeclared-cast", mod.relpath, line, name,
                    f"entry {name!r}: accumulate must be the literal "
                    "\"f32\" (the only accumulator regime the "
                    "unstable-reduction rule can verify)"))
                continue
            e.accumulate = acc
        if "oracle" in fmap:
            orc = _const(fmap["oracle"])
            if not isinstance(orc, str):
                findings.append(Finding(
                    "undeclared-cast", mod.relpath, line, name,
                    f"entry {name!r}: oracle must be a string literal "
                    "TOLERANCE_POLICY path"))
                continue
            e.oracle = orc
        entries.append(e)
    return entries


def _resolve_entry_fn(mod: L.ModuleInfo, name: str) -> Optional[ast.AST]:
    fn = mod.functions.get(name)
    if fn is not None:
        return fn
    hit = L._suffix_index(mod).get(name)
    return hit[1] if hit is not None else None


# -- AST half ----------------------------------------------------------------


def _module_has_low_precision(mod: L.ModuleInfo) -> Optional[int]:
    """First line referencing a sub-f32 value dtype: ``jnp.bfloat16`` /
    ``.int8`` / ``.float16`` attributes, or a string constant EXACTLY
    equal to one of those names anywhere in the tree (call args,
    name-bound module constants like ``KV_DTYPE = "int8"``, dtype
    comparisons). Exact equality keeps docstrings/comments out — a
    prose sentence mentioning int8 is never the whole constant —
    while a name-bound spelling can't evade the trigger."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Attribute) \
                and node.attr in _LOW_PRECISION_NAMES:
            return node.lineno
        if isinstance(node, ast.Constant) \
                and node.value in _LOW_PRECISION_NAMES:
            return node.lineno
    return None


def _cast_token_of(node: ast.AST) -> Optional[str]:
    """The dtype token a cast argument names: a dtype attribute
    (``jnp.float16``) or string constant maps to its token; an ignored
    integer/bool dtype maps to None (skip); anything dynamic
    (``x.dtype``, a variable) is a ``carried`` boundary."""
    name = None
    if isinstance(node, ast.Attribute):
        # jnp.float16 — but x.dtype (attr "dtype") is dynamic
        if node.attr == "dtype":
            return "carried"
        name = node.attr
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    if name is not None:
        if name in _IGNORED_DTYPES:
            return None
        if name in _DTYPE_TOKENS:
            return _DTYPE_TOKENS[name]
        if name in _LOW_PRECISION_NAMES:
            # a low-precision spelling outside the token map (fp8):
            # conservative — treat as its own undeclarable token
            return name
        return "carried" if isinstance(node, ast.Attribute) else name
    return "carried"


def _cast_sites(fn: ast.AST) -> List[Tuple[int, Optional[str], str]]:
    """(line, token, spelling) per cast call in the body: ``.astype(d)``
    and ``[jax.]lax.convert_element_type(x, d)``."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "astype" \
                and node.args:
            out.append((node.lineno, _cast_token_of(node.args[0]),
                        "astype"))
        elif isinstance(f, ast.Attribute) \
                and f.attr == "convert_element_type":
            arg = (node.args[1] if len(node.args) > 1 else
                   next((kw.value for kw in node.keywords
                         if kw.arg == "new_dtype"), None))
            if arg is not None:
                out.append((node.lineno, _cast_token_of(arg),
                            "convert_element_type"))
    return out


# -- jaxpr half --------------------------------------------------------------


class TracedEntry:
    """One production entry point traced at representative avals.

    ``build`` is called lazily (imports jax + the target module) and
    returns ``(fn, args)`` for ``jax.make_jaxpr(fn)(*args)``. The
    (relpath, entry) pair joins the trace to its declared contract."""

    def __init__(self, relpath: str, entry: str,
                 build: Callable[[], tuple]):
        self.relpath = relpath
        self.entry = entry
        self.build = build


def traced_entry_points() -> List[TracedEntry]:
    """The production trace table: the mixed-precision entry points of
    ops/layers.py, ops/quant.py (XLA lowerings — the Pallas kernels'
    bodies are checked by the AST half), ops/kv_quant.py (the quantized
    KV-block movers), models/moe.py's expert contractions, and
    runtime/engine.py's samplers, each at the low-precision avals
    serving actually runs them with. Kept beside the rules so adding a
    traced entry and its contract is one review."""
    import jax.numpy as jnp

    def bf(*s):
        return jnp.zeros(s, jnp.bfloat16)

    def f32(*s):
        return jnp.zeros(s, jnp.float32)

    def i8(*s):
        return jnp.zeros(s, jnp.int8)

    def i32(*s):
        return jnp.zeros(s, jnp.int32)

    def _layers():
        from llm_sharding_demo_tpu.ops import layers
        return layers

    def _quant():
        from llm_sharding_demo_tpu.ops import quant
        return quant

    def _kvq():
        from llm_sharding_demo_tpu.ops import kv_quant
        return kv_quant

    def _moe():
        from llm_sharding_demo_tpu.models import moe
        return moe

    def _engine():
        from llm_sharding_demo_tpu.runtime import engine
        return engine

    LAYERS = "llm_sharding_demo_tpu/ops/layers.py"
    QUANT = "llm_sharding_demo_tpu/ops/quant.py"
    KVQ = "llm_sharding_demo_tpu/ops/kv_quant.py"
    MOE = "llm_sharding_demo_tpu/models/moe.py"
    ENGINE = "llm_sharding_demo_tpu/runtime/engine.py"
    return [
        TracedEntry(LAYERS, "layer_norm", lambda: (
            _layers().layer_norm, (bf(2, 8), f32(8), f32(8)))),
        TracedEntry(LAYERS, "rms_norm", lambda: (
            _layers().rms_norm, (bf(2, 8), bf(8)))),
        TracedEntry(LAYERS, "gelu_new", lambda: (
            _layers().gelu_new, (bf(2, 8),))),
        TracedEntry(QUANT, "quant_matmul", lambda: (
            lambda x, q, s: _quant().quant_matmul(
                x, _quant().QuantizedTensor(q, s)),
            (bf(2, 8), jnp.zeros((8, 16), jnp.int8), bf(16)))),
        TracedEntry(QUANT, "head_logits", lambda: (
            lambda h, q, s: _quant().head_logits(
                h, _quant().QuantizedTensor(q, s)),
            (bf(1, 1, 8), jnp.zeros((16, 8), jnp.int8), bf(8)))),
        TracedEntry(QUANT, "embed_rows", lambda: (
            lambda q, s, ids: _quant().embed_rows(
                _quant().QuantizedTensor(q, s), ids),
            (jnp.zeros((16, 8), jnp.int8), bf(8),
             jnp.zeros((2, 3), jnp.int32)))),
        TracedEntry(QUANT, "quantize_array", lambda: (
            _quant().quantize_array, (f32(8, 16),))),
        # quantized KV-block movers at the tiny paged geometry
        # (L=1, NB=2 + trash, Hkv=2, bs=4, hd=4, B=1, NBm=2)
        TracedEntry(KVQ, "quantize_blocks_int8", lambda: (
            _kvq().quantize_blocks_int8, (f32(2, 2, 4, 4),))),
        TracedEntry(KVQ, "quantize_blocks_fp8", lambda: (
            _kvq().quantize_blocks_fp8, (f32(2, 2, 4, 4),))),
        TracedEntry(KVQ, "dequantize_blocks", lambda: (
            lambda q, s: _kvq().dequantize_blocks(q, s, jnp.float32),
            (i8(2, 2, 4, 4), f32(2, 2)))),
        TracedEntry(KVQ, "gather_kv_q", lambda: (
            lambda d, s, t: _kvq().gather_kv_q(d, s, t, jnp.float32),
            (i8(1, 3, 2, 2, 4, 4), f32(1, 3, 2, 2), i32(1, 2)))),
        TracedEntry(KVQ, "scatter_kv_int8", lambda: (
            _kvq().scatter_kv_int8,
            (i8(1, 3, 2, 2, 4, 4), f32(1, 3, 2, 2),
             f32(1, 1, 2, 8, 4), f32(1, 1, 2, 8, 4), i32(1, 2)))),
        TracedEntry(KVQ, "scatter_kv_fp8", lambda: (
            _kvq().scatter_kv_fp8,
            (jnp.zeros((1, 3, 2, 2, 4, 4), jnp.float8_e4m3fn),
             f32(1, 3, 2, 2),
             f32(1, 1, 2, 8, 4), f32(1, 1, 2, 8, 4), i32(1, 2)))),
        TracedEntry(KVQ, "copy_blocks_q", lambda: (
            _kvq().copy_blocks_q,
            (i8(1, 3, 2, 2, 4, 4), f32(1, 3, 2, 2), i32(1), i32(1)))),
        # MoE expert contractions at the serving int8 x bf16 avals
        TracedEntry(MOE, "_expert_einsum", lambda: (
            lambda x, q, s: _moe()._expert_einsum(
                "ebcd,edf->ebcf", x, _quant().QuantizedTensor(q, s)),
            (bf(2, 2, 2, 8), i8(2, 8, 16), bf(2, 16)))),
        TracedEntry(MOE, "_gathered_einsum", lambda: (
            lambda x, q, s: _moe()._gathered_einsum(
                x, _quant().QuantizedTensor(q, s)),
            (bf(2, 8), i8(2, 8, 16), bf(2, 16)))),
        TracedEntry(ENGINE, "sampler_pmf", lambda: (
            lambda lg: _engine().sampler_pmf(
                lg, _engine().SamplingConfig(mode="sample")),
            (bf(2, 64),))),
        TracedEntry(ENGINE, "select_token", lambda: (
            lambda lg: _engine().select_token(
                lg, _engine().SamplingConfig(), None),
            (f32(2, 64),))),
    ]


def _dtype_token(dtype) -> Optional[str]:
    name = getattr(dtype, "name", str(dtype))
    if name in _IGNORED_DTYPES:
        return None
    return _DTYPE_TOKENS.get(name, name)


def _token_width(token: Optional[str]) -> Optional[int]:
    return _TOKEN_WIDTH.get(token) if token is not None else None


def _is_float(aval) -> bool:
    import jax.numpy as jnp
    return jnp.issubdtype(aval.dtype, jnp.floating)


def _walk_eqns(jaxpr):
    from .semantic import _sub_jaxprs
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub)


_ACCUM_REDUCES = ("reduce_sum", "reduce_prod", "cumsum", "cumprod",
                  "cumlogsumexp")


def _check_traced(entry: TracedEntry, contract: _Entry, path: str,
                  line: int, findings: List[Finding]) -> int:
    """Trace one entry and run the three jaxpr rules against its
    declared contract. Returns checks performed."""
    import jax
    import jax.numpy as jnp

    fn, args = entry.build()
    closed = jax.make_jaxpr(fn)(*args)
    flat_in, _ = jax.tree_util.tree_flatten(args)
    in_float_dtypes = {a.dtype for a in flat_in
                       if hasattr(a, "dtype")
                       and jnp.issubdtype(a.dtype, jnp.floating)}
    carried_width = None
    for a in flat_in:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            carried_width = _token_width(_dtype_token(a.dtype)) or 32
            break
    checks = 0
    scope = entry.entry

    low_accum_eqns = 0
    for eqn in _walk_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim == "convert_element_type":
            checks += 1
            op = eqn.invars[0]
            src = getattr(getattr(op, "aval", None), "dtype", None)
            dst = eqn.params.get("new_dtype")
            if src is None or dst is None or src == dst:
                continue
            if getattr(op.aval, "ndim", 0) == 0:
                continue  # scalar parameter casts are not value streams
            token = _dtype_token(dst)
            if token is None:
                continue  # integer/index cast: control flow
            sanctioned = token in contract.casts or (
                "carried" in contract.casts and dst in in_float_dtypes)
            if not sanctioned:
                findings.append(Finding(
                    "undeclared-cast", path, line, scope,
                    f"traced {entry.entry} converts "
                    f"{getattr(src, 'name', src)} -> "
                    f"{getattr(dst, 'name', dst)} but {token!r} is not "
                    "a declared cast boundary of this entry "
                    f"(casts: {sorted(contract.casts)})"))
        elif prim == "dot_general" or prim in _ACCUM_REDUCES:
            ops_low = [v for v in eqn.invars
                       if getattr(getattr(v, "aval", None), "dtype", None)
                       is not None
                       and _token_width(_dtype_token(v.aval.dtype))
                       not in (None, 32, 64)]
            if not ops_low:
                continue
            checks += 1
            low_accum_eqns += 1
            pet = eqn.params.get("preferred_element_type")
            pet_ok = pet is not None and (
                _token_width(_dtype_token(jnp.dtype(pet))) or 0) >= 32
            out_ok = all(
                (_token_width(_dtype_token(v.aval.dtype)) or 32) >= 32
                for v in eqn.outvars if _is_float(v.aval))
            if not (pet_ok or out_ok):
                findings.append(Finding(
                    "unstable-reduction", path, line, scope,
                    f"traced {entry.entry}: {prim} over "
                    f"{sorted(v.aval.dtype.name for v in ops_low)} "
                    "avals accumulates below f32 (no "
                    "preferred_element_type and a sub-f32 output) — "
                    "the declared f32-accumulator discipline is not "
                    "established in the program"))
    if low_accum_eqns and contract.accumulate != "f32":
        checks += 1
        findings.append(Finding(
            "unstable-reduction", path, line, scope,
            f"traced {entry.entry} contains {low_accum_eqns} low-"
            "precision dot/reduce equation(s) but its contract declares "
            "no accumulate: \"f32\" — the accumulator discipline must "
            "be declared, not implied"))

    # silent-downcast: the output boundary against the declared regime
    checks += 1
    regime_width = (_TOKEN_WIDTH.get(contract.regime)
                    if contract.regime != "carried" else carried_width)
    if regime_width is not None:
        for aval in closed.out_avals:
            if not hasattr(aval, "dtype") or not _is_float(aval):
                continue
            w = _token_width(_dtype_token(aval.dtype)) or 32
            if w < regime_width:
                findings.append(Finding(
                    "silent-downcast", path, line, scope,
                    f"traced {entry.entry} returns "
                    f"{aval.dtype.name} from a declared "
                    f"{contract.regime!r} regime (width {regime_width})"
                    " — an undeclared narrowing at the jit boundary"))
    return checks


# -- tolerance-policy registry (graftnum.py, read statically) ----------------


def _parse_policy(mod: Optional[L.ModuleInfo],
                  findings: List[Finding]) -> Tuple[Dict[str, dict], int]:
    """graftnum's TOLERANCE_POLICY -> {path: {metric: value}} + decl
    line; malformed shapes are approx-without-oracle findings against
    the graftnum file itself."""
    if mod is None:
        return {}, 0
    stmt = _module_assign(mod, "TOLERANCE_POLICY")
    if stmt is None:
        findings.append(Finding(
            "approx-without-oracle", mod.relpath, 1, "<module>",
            "graftnum declares no TOLERANCE_POLICY — the approximate "
            "paths have no registered budgets"))
        return {}, 0
    line = stmt.lineno
    items = _str_dict_items(stmt.value)
    if items is None:
        findings.append(Finding(
            "approx-without-oracle", mod.relpath, line, "<module>",
            "TOLERANCE_POLICY must be a dict literal keyed by path"))
        return {}, line
    out: Dict[str, dict] = {}
    for path_name, spec in items:
        metrics = _str_dict_items(spec)
        vals = {}
        ok = metrics is not None
        if ok:
            for m, v in metrics:
                c = _const(v)
                if m not in ORACLE_METRICS or not isinstance(
                        c, (int, float)) or isinstance(c, bool):
                    ok = False
                    break
                vals[m] = float(c)
            ok = ok and set(vals) == set(ORACLE_METRICS)
        if not ok:
            findings.append(Finding(
                "approx-without-oracle", mod.relpath, line, path_name,
                f"TOLERANCE_POLICY[{path_name!r}] must declare exactly "
                f"the numeric metrics {ORACLE_METRICS} (a cap and a "
                "floor — a partial budget gates nothing)"))
            continue
        out[path_name] = vals
    return out, line


# -- the pass ----------------------------------------------------------------


def run_numerics(root: str, paths: Optional[List[str]] = None,
                 traced: Optional[Sequence[TracedEntry]] = None,
                 policy: Optional[Dict[str, dict]] = None,
                 trace: bool = True,
                 ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``numerics_checks`` (contract entries validated + cast
    sites scanned + traced-rule evaluations — the vacuity guard on the
    pass itself), ``numerics_contracts`` (per-module live entry count)
    and ``vacuous`` (modules whose contract resolves to zero live
    functions — the strict driver fails these). ``paths`` / ``traced``
    / ``policy`` are injectable for rule fixtures; ``trace=False``
    (lint-only mode) keeps the pass jax-free."""
    findings: List[Finding] = []
    checks = 0
    contracts: Dict[str, int] = {}
    vacuous: List[str] = []

    scan_paths = paths if paths is not None else L.iter_sources(root)
    mods: Dict[str, L.ModuleInfo] = {}
    for path in scan_paths:
        mod = L.index_module(path, root)
        if mod is not None:
            mods[mod.relpath] = mod

    # tolerance-policy registry (injectable; default: graftnum.py's own
    # declaration, parsed statically)
    policy_line = 0
    if policy is None:
        gmod = mods.get(GRAFTNUM_RELPATH)
        if gmod is None and paths is None:
            import os
            gpath = os.path.join(root, GRAFTNUM_RELPATH)
            if os.path.exists(gpath):
                gmod = L.index_module(gpath, root)
        if gmod is not None:
            policy, policy_line = _parse_policy(gmod, findings)
            checks += 1
        else:
            policy = {}
    oracle_refs: Set[str] = set()

    entries_by_mod: Dict[str, Dict[str, _Entry]] = {}
    for relpath, mod in sorted(mods.items()):
        in_scope = relpath.startswith("llm_sharding_demo_tpu/ops/") or \
            relpath.startswith("llm_sharding_demo_tpu/runtime/") or \
            relpath.startswith("llm_sharding_demo_tpu/models/") or \
            (paths is not None and ("/ops/" in "/" + relpath
                                    or "/runtime/" in "/" + relpath
                                    or "/models/" in "/" + relpath))
        entries = _parse_contract(mod, findings)
        if entries is None:
            if in_scope:
                low_line = _module_has_low_precision(mod)
                if low_line is not None:
                    checks += 1
                    findings.append(Finding(
                        "undeclared-cast", relpath, low_line, "<module>",
                        "module references sub-f32 dtypes but declares "
                        "no PRECISION_CONTRACT — low-precision "
                        "arithmetic must declare its regime, cast "
                        "boundaries, and exactness (docs/ARCHITECTURE."
                        "md 'Numerics discipline')"))
            continue
        checks += 1
        live = 0
        emap: Dict[str, _Entry] = {}
        for e in entries:
            checks += 1
            fn = _resolve_entry_fn(mod, e.name)
            if fn is None:
                findings.append(Finding(
                    "undeclared-cast", relpath, e.line, e.name,
                    f"PRECISION_CONTRACT names {e.name!r} but no such "
                    "function exists in this module (stale "
                    "declaration)"))
                continue
            live += 1
            emap[e.name] = e
            # AST cast scan over the entry's body
            for cline, token, spelling in _cast_sites(fn):
                if token is None:
                    continue
                checks += 1
                if token not in e.casts:
                    findings.append(Finding(
                        "undeclared-cast", relpath, cline, e.name,
                        f"{spelling} to {token!r} is not a declared "
                        f"cast boundary of entry {e.name!r} (casts: "
                        f"{sorted(e.casts)}) — sanction it in "
                        "PRECISION_CONTRACT or keep the value in its "
                        "declared regime"))
            # oracle discipline
            checks += 1
            if e.exact is False:
                if e.oracle is None:
                    findings.append(Finding(
                        "approx-without-oracle", relpath, e.line, e.name,
                        f"entry {e.name!r} declares exact: False but "
                        "maps to no tolerance oracle — every "
                        "approximate path needs a declared "
                        "TOLERANCE_POLICY budget (graftnum)"))
                elif e.oracle not in policy:
                    findings.append(Finding(
                        "approx-without-oracle", relpath, e.line, e.name,
                        f"entry {e.name!r} maps to oracle path "
                        f"{e.oracle!r}, which TOLERANCE_POLICY does not "
                        f"declare (declared: {sorted(policy)})"))
                else:
                    oracle_refs.add(e.oracle)
            elif e.exact is True and e.oracle is not None:
                findings.append(Finding(
                    "approx-without-oracle", relpath, e.line, e.name,
                    f"entry {e.name!r} declares exact: True AND an "
                    f"oracle path {e.oracle!r} — a byte-equality pin "
                    "must not claim an approx-declared path (pick "
                    "one)"))
        entries_by_mod[relpath] = emap
        contracts[relpath] = live
        if live == 0:
            vacuous.append(relpath)

    # stale policy paths: budgets no contract routes to
    for path_name in sorted(set(policy) - oracle_refs):
        checks += 1
        findings.append(Finding(
            "approx-without-oracle", GRAFTNUM_RELPATH, policy_line or 1,
            path_name,
            f"TOLERANCE_POLICY declares path {path_name!r} but no "
            "PRECISION_CONTRACT entry maps to it (stale budget — or an "
            "approximate path lost its declaration)"))

    # jaxpr half
    if trace:
        for t in (traced if traced is not None else traced_entry_points()):
            emap = entries_by_mod.get(t.relpath, {})
            e = emap.get(t.entry)
            checks += 1
            if e is None:
                findings.append(Finding(
                    "undeclared-cast", t.relpath, 1, t.entry,
                    f"traced entry point {t.entry!r} has no "
                    "PRECISION_CONTRACT entry — its casts and "
                    "accumulators are unreviewable"))
                continue
            fn_node = (_resolve_entry_fn(mods[t.relpath], t.entry)
                       if t.relpath in mods else None)
            line = getattr(fn_node, "lineno", e.line)
            checks += _check_traced(t, e, t.relpath, line, findings)

    summary = {
        "numerics_checks": checks,
        "numerics_contracts": contracts,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
