"""graftcheck: compile-free contract verifier + TPU-footgun lint suite.

Two passes, one entry point (``python -m tools.graftcheck``; in-suite
driver ``tests/test_graftcheck.py``):

- **Pass 1 — semantic** (``semantic``, ``recompile``): abstract
  evaluation (``jax.eval_shape`` / ``jax.make_jaxpr`` on CPU-mesh
  stand-ins — no compute, no TPU, no XLA compile of model programs) of
  the contracts the runtime otherwise only checks by executing them:
  inter-stage shape/dtype contracts for every registered family x
  partition plan, PartitionSpec validity against the mesh, ``ppermute``
  bijection over the stage axis, and a recompile-budget certifier that
  statically bounds the jitted-program space per serving config.
- **Pass 2 — lint** (``lint``): AST rules for TPU serving footguns —
  host syncs in decode hot loops, ``jax.jit`` in per-request scope,
  implicitly captured closure state in jitted functions, wall-clock
  reads under jit, metrics/tracing calls under jit (silent no-ops), and
  the metric-name catalog (the former ``tools/check_metrics.py``, now a
  rule here).
- **Pass 3 — graftsan sanitize** (``sanitize``): donation-aliasing
  rules — ``DONATED_ARGS`` declaration consistency (the undeclared-jit
  idiom for ``donate_argnums``), host views of values that flow into
  donated arguments (the PR 5 ``_SegOut`` bug shape), donated-buffer
  re-reads, and pool movers outside declared ``POOL_MOVER_SCOPES``
  lease scopes. Its dynamic half (``GRAFTSAN=1`` — poisoning,
  refcount conservation, leak provenance) lives in
  ``runtime.kv_pool``.
- **Pass 4 — graftlock locks** (``locks``): lock-discipline rules —
  ``GUARDED_STATE``/``LOCK_ORDER``/``DEVICE_LOCKS`` declaration
  consistency, guarded state touched without its hold (or escaping a
  region via return), acquisition orders contradicting the declared
  order or each other (tracked through same-module calls),
  check-then-act across separate holds, and blocking work under a
  lock. Its dynamic half (``GRAFTSCHED=1`` — traced locks, seeded
  deterministic schedules, deadlock timeout, contention accounting)
  lives in ``llm_sharding_demo_tpu.utils.graftsched``.

Findings are suppressed per (rule, file, scope) by
``tools/graftcheck/baseline.txt`` — one line per intentional keep, with
a justification. Anything not baselined fails the run.
"""

from .core import Finding, load_baseline, split_findings  # noqa: F401

__all__ = ["Finding", "load_baseline", "split_findings"]
