"""graftcheck timeline pass: declared-event static analysis (compile-free).

The grafttime bus (``llm_sharding_demo_tpu/utils/grafttime.py``) only
earns the name "unified causal timeline" if every producer actually
publishes what it claims and nothing publishes off-vocabulary — a
timeline with silent gaps is worse than silos, because it LOOKS
complete. This pass (the static half of grafttime, riding ``python -m
tools.graftcheck`` and the strict in-suite driver, mirroring the
slo/watch emission-scan split) holds the declarations to that bar:

In-file declarations (the registration-annotation idiom of
``FAULT_POLICY`` / ``SLO_POLICY`` / ``PLAN_SIGNALS``):

- ``TIMELINE_EVENTS``: ``{kind: source}`` — which vocabulary kinds this
  module publishes and from where (source is reviewable provenance
  prose; the kind set is what the pass verifies).

The fixed vocabulary and the per-kind required fields live in
``grafttime.EVENT_KINDS`` / ``grafttime.KIND_FIELDS`` (injectable here
for fixtures).

Rules (ids in brackets; suppressions ride the shared baseline):

- [undeclared-timeline-event]   an ``grafttime.emit(...)`` call whose
                                kind is not a string literal (a dynamic
                                kind is unreviewable), is outside the
                                fixed vocabulary, or is not declared in
                                the module's TIMELINE_EVENTS; a
                                malformed declaration (non-literal
                                dict, non-string source); an emit site
                                missing a required correlator/payload
                                keyword for its kind
                                (``grafttime.KIND_FIELDS`` — the value
                                may be None at runtime, but the site
                                must SPELL the field).
- [timeline-event-not-emitted]  a declared kind with no emit site in
                                the module (stale declaration — the
                                producer stopped publishing and the
                                timeline silently lost a signal), or a
                                declared kind outside the vocabulary.

Export schema: the pass additionally builds one schema-complete
synthetic event per vocabulary kind (``grafttime.sample_event``), runs
it through ``export_chrome`` + ``validate_chrome``, and fails on any
schema problem — the Chrome-trace export cannot drift invalid without
failing CI, compile-free.

``--strict`` additionally fails a VACUOUS pass (a module declaring
TIMELINE_EVENTS none of whose kinds are emitted — the producer went
dark); ``cli.run --json`` carries ``timeline_checks`` /
``timeline_kinds`` / ``timeline_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign

TIMELINE_RULE_IDS = ("undeclared-timeline-event",
                     "timeline-event-not-emitted")


class _EmitSite:
    __slots__ = ("kind", "line", "scope", "kwargs", "literal")

    def __init__(self, kind, line, scope, kwargs, literal):
        self.kind = kind          # str or None (non-literal)
        self.line = line
        self.scope = scope
        self.kwargs = kwargs      # keyword names spelled at the site
        self.literal = literal


class _EmitScanner(ast.NodeVisitor):
    """Collect ``grafttime.emit("<kind>", ...)`` call sites with their
    enclosing scope and spelled keyword names."""

    def __init__(self):
        self.sites: List[_EmitSite] = []
        self._scope = ["<module>"]

    def _visit_func(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_Call(self, node):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr == "emit"
                and isinstance(f.value, ast.Name)
                and f.value.id == "grafttime"):
            kind = None
            literal = False
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                kind = node.args[0].value
                literal = True
            kwargs = {kw.arg for kw in node.keywords
                      if kw.arg is not None}
            self.sites.append(_EmitSite(kind, node.lineno,
                                        self._scope[-1], kwargs,
                                        literal))
        self.generic_visit(node)


def _declared_events(stmt: ast.Assign
                     ) -> Optional[List[Tuple[str, int]]]:
    """TIMELINE_EVENTS dict literal -> [(kind, line)]; None when the
    declaration is not a statically readable string->string dict."""
    node = stmt.value
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            return None
        out.append((k.value, k.lineno))
    return out


def run_timeline(root: str, paths: Optional[List[str]] = None,
                 vocabulary: Optional[Dict[str, str]] = None,
                 kind_fields: Optional[Dict[str, tuple]] = None,
                 check_export: bool = True,
                 ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``timeline_checks`` (declarations + emit sites + export
    kinds validated — the vacuity guard on the pass itself),
    ``timeline_kinds`` (per-module count of declared kinds with a live
    emit site) and ``vacuous`` (modules whose TIMELINE_EVENTS matches
    no emission — the strict driver fails these).
    ``vocabulary``/``kind_fields`` are injectable for rule fixtures; by
    default the real ``grafttime.EVENT_KINDS`` / ``KIND_FIELDS``."""
    if vocabulary is None or kind_fields is None:
        from llm_sharding_demo_tpu.utils import grafttime as GT
        vocabulary = vocabulary if vocabulary is not None \
            else GT.EVENT_KINDS
        kind_fields = kind_fields if kind_fields is not None \
            else GT.KIND_FIELDS

    findings: List[Finding] = []
    checks = 0
    kinds_live: Dict[str, int] = {}
    vacuous: List[str] = []

    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        if mod.relpath == "llm_sharding_demo_tpu/utils/grafttime.py":
            # the bus itself is the apparatus, not a producer (the
            # graftsched-exemption precedent)
            continue
        decl_stmt = _module_assign(mod, "TIMELINE_EVENTS")
        scanner = _EmitScanner()
        scanner.visit(mod.tree)
        sites = scanner.sites
        if decl_stmt is None and not sites:
            continue
        checks += 1

        declared: Dict[str, int] = {}
        if decl_stmt is not None:
            entries = _declared_events(decl_stmt)
            if entries is None:
                findings.append(Finding(
                    "undeclared-timeline-event", mod.relpath,
                    decl_stmt.lineno, "<module>",
                    "TIMELINE_EVENTS must be a dict literal of string "
                    "kind -> string source (the timeline pass reads it "
                    "statically)"))
            else:
                declared = dict(entries)
        elif sites:
            findings.append(Finding(
                "undeclared-timeline-event", mod.relpath,
                sites[0].line, sites[0].scope,
                f"module emits {len(sites)} timeline event(s) but "
                "declares no TIMELINE_EVENTS — declare {kind: source} "
                "so the producer set is reviewable"))

        emitted_kinds = set()
        for s in sites:
            checks += 1
            if not s.literal:
                findings.append(Finding(
                    "undeclared-timeline-event", mod.relpath, s.line,
                    s.scope,
                    "grafttime.emit kind must be a string literal from "
                    "the fixed vocabulary (a computed kind is "
                    "unreviewable and unjoinable)"))
                continue
            emitted_kinds.add(s.kind)
            if s.kind not in vocabulary:
                findings.append(Finding(
                    "undeclared-timeline-event", mod.relpath, s.line,
                    s.scope,
                    f"timeline kind {s.kind!r} is outside the fixed "
                    f"vocabulary ({sorted(vocabulary)}) — a new event "
                    "class is a reviewed grafttime.EVENT_KINDS change"))
                continue
            if declared and s.kind not in declared:
                findings.append(Finding(
                    "undeclared-timeline-event", mod.relpath, s.line,
                    s.scope,
                    f"timeline kind {s.kind!r} is emitted here but not "
                    "declared in this module's TIMELINE_EVENTS"))
            missing = [f for f in kind_fields.get(s.kind, ())
                       if f not in s.kwargs]
            if missing:
                findings.append(Finding(
                    "undeclared-timeline-event", mod.relpath, s.line,
                    s.scope,
                    f"timeline kind {s.kind!r} emit site does not "
                    f"spell required field(s) {missing} — the schema "
                    "(grafttime.KIND_FIELDS) makes correlators "
                    "reviewable at every site"))

        live = 0
        for kind, line in declared.items():
            checks += 1
            if kind not in vocabulary:
                findings.append(Finding(
                    "timeline-event-not-emitted", mod.relpath, line,
                    "<module>",
                    f"TIMELINE_EVENTS declares {kind!r}, which is "
                    f"outside the fixed vocabulary "
                    f"({sorted(vocabulary)})"))
                continue
            if kind in emitted_kinds:
                live += 1
            else:
                findings.append(Finding(
                    "timeline-event-not-emitted", mod.relpath, line,
                    "<module>",
                    f"TIMELINE_EVENTS declares {kind!r} but no "
                    "grafttime.emit site in this module publishes it — "
                    "the timeline silently lost a declared signal "
                    "(stale declaration?)"))
        if declared:
            kinds_live[mod.relpath] = live
            if live == 0:
                vacuous.append(mod.relpath)

    if check_export:
        # export schema validity, one synthetic event per kind: the
        # Chrome-trace mapping cannot drift invalid without a finding
        from llm_sharding_demo_tpu.utils import grafttime as GT
        for kind in sorted(vocabulary):
            checks += 1
            try:
                payload = GT.export_chrome([GT.sample_event(kind)])
                problems = GT.validate_chrome(payload)
            except Exception as e:  # noqa: BLE001 — a crash IS a finding
                problems = [f"{type(e).__name__}: {e}"]
            for p in problems:
                findings.append(Finding(
                    "undeclared-timeline-event",
                    "llm_sharding_demo_tpu/utils/grafttime.py", 1,
                    kind,
                    f"Chrome-trace export of kind {kind!r} is "
                    f"schema-invalid: {p}"))

    summary = {
        "timeline_checks": checks,
        "timeline_kinds": kinds_live,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
