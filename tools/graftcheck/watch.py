"""graftcheck watch pass: declared re-planning contracts (compile-free).

The static half of graftwatch (``llm_sharding_demo_tpu/utils/
graftwatch.py`` is the dynamic half — the same split as
sanitize/locks/faults/slo/fleet). The live re-planner makes control
decisions from telemetry and installs plans at runtime; this pass holds
the two things that make that safe to the declaration bar:

**Signal provenance.** Every signal the watcher consumes is declared in
``PLAN_SIGNALS`` — a mapping from the fixed ``SIGNALS`` vocabulary to
the ``METRIC_CATALOG`` series it is computed from (the mirror of
loadgen's ``SLO_SOURCE_METRICS``). A re-planner steering on a series
nobody emits converges on noise, so the rule verifies each mapped
series exists in the catalog AND is emitted at a live production call
site (the same emission scan the slo pass uses).

**Certified-set membership.** Every plan the switcher can install is
declared in ``PLAN_SET``, and every ``PLAN_SET`` member must be
constructed/priced/certified by the declared ``PLAN_BUILDERS``
functions — both directions checked, so no switch path can reach an
uncertified program key statically (the ``PlanSwitcher`` enforces the
same invariant dynamically with typed errors). Explicit switch targets
(``.switch_to("label")`` string literals anywhere in the scanned tree)
must name ``PLAN_SET`` members.

Rules (ids in brackets; suppressions ride the shared baseline):

- [plan-signal-without-source]   malformed PLAN_SIGNALS/SIGNALS
                                 declarations, a consumed signal with
                                 no mapping, a stale mapping for an
                                 undeclared signal, a mapped series
                                 missing from METRIC_CATALOG, or one no
                                 production call site emits.
- [uncertified-plan-switch]      malformed PLAN_SET/PLAN_BUILDERS, a
                                 builder constructing a label outside
                                 PLAN_SET, a PLAN_SET member no builder
                                 constructs, a missing builder
                                 function, or an explicit switch-target
                                 literal outside PLAN_SET.

``--strict`` additionally fails a VACUOUS pass (a PLAN_SIGNALS
declaration with zero fully-resolved entries, or an empty PLAN_SET);
``cli.run --json`` carries ``watch_checks`` / ``watch_signals`` /
``watch_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign
from .slo import _emitted_metric_names, _str_dict_keys

WATCH_RULE_IDS = ("plan-signal-without-source", "uncertified-plan-switch")

# The fixed consumed-signal vocabulary (graftwatch.SIGNALS mirrors this
# — tests pin the two stay equal, like the slo pass's SLO_METRICS).
WATCH_SIGNALS = ("queue_depth", "batch_occupancy", "pool_blocks",
                 "live_rows", "breaker_open", "prefix_hits",
                 "prefix_misses", "admission_sheds", "affinity_hits",
                 "affinity_fallbacks", "replica_sheds")


def _str_tuple(node: ast.AST) -> Optional[List[str]]:
    """Tuple/list literal of string constants -> the strings; None when
    not that shape."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.append(e.value)
    return out


def _function_defs(mod: L.ModuleInfo) -> Dict[str, ast.AST]:
    """Top-level function defs by bare name (builders are module-level
    functions by convention; the qualname map also covers methods)."""
    out: Dict[str, ast.AST] = {}
    for qual, node in mod.functions.items():
        out.setdefault(qual.rpartition(".")[2], node)
        out[qual] = node
    return out


def _dicts_str_keys_in(node: ast.AST) -> List[Set[str]]:
    """Per dict literal inside ``node``, its string keys — the watch
    pass identifies PLAN-SHAPED dicts (any key is a PLAN_SET label) and
    holds all of THAT dict's keys to label discipline, so builders'
    payload dicts (``{"programs": ...}``) never false-positive."""
    out: List[Set[str]] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Dict):
            keys = {k.value for k in sub.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if keys:
                out.append(keys)
    return out


def _switch_target_literals(tree: ast.AST) -> List[Tuple[int, str]]:
    """(line, label) for every ``<x>.switch_to("label")`` call with a
    string-literal first argument."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "switch_to" and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                out.append((node.lineno, a0.value))
    return out


def run_watch(root: str, paths: Optional[List[str]] = None,
              catalog: Optional[Dict[str, str]] = None,
              emitted: Optional[Set[str]] = None,
              ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``watch_checks`` (declarations + per-signal resolutions —
    the vacuity guard on the pass itself), ``watch_signals``
    (per-module count of fully-resolved signal mappings) and
    ``vacuous`` (modules whose declarations resolve to nothing live —
    the strict driver fails these). ``catalog``/``emitted`` are
    injectable for rule fixtures."""
    if catalog is None:
        from llm_sharding_demo_tpu.utils.metrics import METRIC_CATALOG
        catalog = METRIC_CATALOG
    if emitted is None:
        emitted = _emitted_metric_names(root, paths=paths)

    findings: List[Finding] = []
    checks = 0
    signals_resolved: Dict[str, int] = {}
    vacuous: List[str] = []

    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        sig_stmt = _module_assign(mod, "PLAN_SIGNALS")
        set_stmt = _module_assign(mod, "PLAN_SET")
        if sig_stmt is None and set_stmt is None:
            continue
        checks += 1

        # -- signal provenance ------------------------------------------------
        if sig_stmt is not None:
            vocab_stmt = _module_assign(mod, "SIGNALS")
            vocab = (_str_tuple(vocab_stmt.value)
                     if vocab_stmt is not None else None)
            if vocab_stmt is not None and vocab is None:
                findings.append(Finding(
                    "plan-signal-without-source", mod.relpath,
                    vocab_stmt.lineno, "<module>",
                    "SIGNALS must be a tuple/list literal of string "
                    "signal names (the watch pass reads the vocabulary "
                    "statically)"))
                vocab = []
            entries = _str_dict_keys(sig_stmt.value)
            line = sig_stmt.lineno
            resolved = 0
            if entries is None:
                findings.append(Finding(
                    "plan-signal-without-source", mod.relpath, line,
                    "<module>",
                    "PLAN_SIGNALS must be a dict literal mapping each "
                    "consumed signal to its METRIC_CATALOG series"))
            else:
                declared = {k for k, _ in entries}
                for name in sorted(set(vocab or ()) - declared):
                    checks += 1
                    findings.append(Finding(
                        "plan-signal-without-source", mod.relpath,
                        line, name,
                        f"consumed signal {name!r} has no PLAN_SIGNALS "
                        "mapping — which METRIC_CATALOG series is the "
                        "re-planner watching for it?"))
                for name, value in entries:
                    checks += 1
                    if vocab is not None and name not in vocab:
                        findings.append(Finding(
                            "plan-signal-without-source", mod.relpath,
                            line, name,
                            f"PLAN_SIGNALS declares {name!r} but it is "
                            "not in the SIGNALS vocabulary (stale "
                            "declaration)"))
                        continue
                    if not (isinstance(value, ast.Constant)
                            and isinstance(value.value, str)):
                        findings.append(Finding(
                            "plan-signal-without-source", mod.relpath,
                            line, name,
                            f"signal {name!r}: the mapped series must "
                            "be a string literal METRIC_CATALOG name"))
                        continue
                    series = value.value
                    if series not in catalog:
                        findings.append(Finding(
                            "plan-signal-without-source", mod.relpath,
                            line, name,
                            f"signal {name!r} maps to {series!r}, which "
                            "is not in METRIC_CATALOG — the re-planner "
                            "would watch a series that does not exist"))
                        continue
                    if series not in emitted:
                        findings.append(Finding(
                            "plan-signal-without-source", mod.relpath,
                            line, name,
                            f"signal {name!r} maps to {series!r}, which "
                            "no production call site emits — a "
                            "re-planner steering on a series nobody "
                            "measures converges on noise"))
                        continue
                    resolved += 1
            signals_resolved[mod.relpath] = resolved
            if resolved == 0:
                vacuous.append(mod.relpath)

        # -- certified-set membership -----------------------------------------
        if set_stmt is not None:
            plan_set = _str_tuple(set_stmt.value)
            line = set_stmt.lineno
            if plan_set is None or not plan_set:
                findings.append(Finding(
                    "uncertified-plan-switch", mod.relpath, line,
                    "<module>",
                    "PLAN_SET must be a non-empty tuple/list literal of "
                    "string plan labels — the switchable set the "
                    "certifier prices"))
                if mod.relpath not in vacuous:
                    vacuous.append(mod.relpath)
                plan_set = []
            builders_stmt = _module_assign(mod, "PLAN_BUILDERS")
            builder_names = (_str_tuple(builders_stmt.value)
                             if builders_stmt is not None else None)
            if plan_set and builder_names is None:
                findings.append(Finding(
                    "uncertified-plan-switch", mod.relpath,
                    (builders_stmt.lineno if builders_stmt is not None
                     else line), "<module>",
                    "a module declaring PLAN_SET must declare "
                    "PLAN_BUILDERS (tuple literal of the functions that "
                    "construct/price/certify the plan set) — otherwise "
                    "certified-set membership is unreviewable"))
            constructed: Set[str] = set()
            defs = _function_defs(mod)
            for bname in builder_names or ():
                checks += 1
                fn = defs.get(bname)
                if fn is None:
                    findings.append(Finding(
                        "uncertified-plan-switch", mod.relpath,
                        (builders_stmt.lineno
                         if builders_stmt is not None else line), bname,
                        f"PLAN_BUILDERS names {bname!r} but no such "
                        "function exists in this module (stale "
                        "declaration)"))
                    continue
                for keys in _dicts_str_keys_in(fn):
                    if not keys & set(plan_set):
                        continue          # payload dict, not plan-shaped
                    for label in sorted(keys - set(plan_set)):
                        checks += 1
                        findings.append(Finding(
                            "uncertified-plan-switch", mod.relpath,
                            fn.lineno, bname,
                            f"builder {bname!r} constructs plan label "
                            f"{label!r} beside declared PLAN_SET "
                            f"labels {tuple(plan_set)} — an "
                            "uncertified label the switcher could "
                            "reach"))
                    constructed |= keys & set(plan_set)
            for label in plan_set:
                checks += 1
                if builder_names and label not in constructed:
                    findings.append(Finding(
                        "uncertified-plan-switch", mod.relpath, line,
                        label,
                        f"PLAN_SET declares {label!r} but no "
                        "PLAN_BUILDERS function constructs it — a "
                        "switch target with no certified runner"))
            for lineno, label in _switch_target_literals(mod.tree):
                checks += 1
                if label not in plan_set:
                    findings.append(Finding(
                        "uncertified-plan-switch", mod.relpath, lineno,
                        label,
                        f"explicit switch target {label!r} is outside "
                        f"the declared PLAN_SET {tuple(plan_set)}"))

    summary = {
        "watch_checks": checks,
        "watch_signals": signals_resolved,
        "vacuous": sorted(set(vacuous)),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
