"""graftcheck locks pass: lock-discipline static analysis (compile-free).

The serving/runtime layer is genuinely concurrent — ``ThreadingHTTPServer``
request handlers feed background scheduler threads (``runtime/batcher.py``,
``runtime/iterbatch.py``) over shared ``BlockAllocator``/prefix-store/
metrics/tracing state guarded by a dozen ad-hoc locks — yet until this
pass the graftcheck spine proved nothing about locking. Mirroring
graftsan's static+dynamic split, this module is the STATIC half: locking
becomes a DECLARED contract, and an AST dataflow pass (the same
scope/qualname machinery as ``sanitize.py``) enforces it over the
production tree. The dynamic half — the ``GRAFTSCHED=1`` cooperative-
schedule race harness — lives in ``llm_sharding_demo_tpu/utils/
graftsched.py`` (which, like any sanitizer runtime, is excluded from its
own instrumentation's scan).

In-file declarations (the registration annotations, same idiom as
``JIT_ENTRY_POINTS`` / ``DONATED_ARGS``):

- ``GUARDED_STATE``: dict literal ``{attr_or_prefix: lock_name}`` — the
  shared mutable attributes this module's locks exist to protect. A key
  ending in ``*`` is a prefix (``"_san_*": "_lock"`` covers every
  sanitizer-bookkeeping attr). Underscore-prefixed attrs are enforced
  ACROSS modules (``iterbatch`` touching ``spec._requests`` is held to
  ``spec_decode``'s declaration); public attrs bind only within the
  declaring module (common names like ``data`` must not contaminate
  unrelated modules).
- ``LOCK_ORDER``: tuple of lock names in permitted acquisition order —
  a lock may only be acquired while holding locks that appear EARLIER.
- ``DEVICE_LOCKS``: tuple of lock names whose documented job is
  serializing device work (the prefix store's donation lock, the pool's
  ``_dev_lock``): jit dispatch and device sync under them is the
  design, not a finding. Host blocking (``requests.*``, ``sleep``,
  ``.result()``, ``.wait()``) is still flagged under every lock.

Every declared lock is CONSTRUCTED through ``utils.graftsched.lock`` /
``.rlock`` (plain ``threading`` objects when GRAFTSCHED is off), which
is what lets the dynamic harness instrument exactly the declared set.

Rules (ids in brackets; suppressions ride the shared baseline):

- [unguarded-state]      read/write of a declared guarded attribute
                         outside a ``with <lock>`` region whose lock
                         name AND receiver match the declaration
                         (``with self._lock`` guards ``self._free``,
                         not ``other._free``); also guarded state
                         ESCAPING a lock region via a bare ``return``,
                         and declaration-consistency findings (a lock
                         constructed but guarding nothing declared, a
                         stale declaration, a threaded module declaring
                         nothing). ``__init__`` bodies (object not yet
                         shared) and ``*_locked``-suffix functions (the
                         repo's caller-holds-the-lock convention) are
                         exempt.
- [lock-order]           an acquisition order contradicting the
                         module's ``LOCK_ORDER``, two call paths
                         acquiring the same two locks in opposite
                         orders (reported once with both sites), or a
                         non-reentrant lock re-acquired on a path that
                         already holds it. Nesting is tracked through
                         same-module calls (one-level resolution +
                         transitive closure), so ``gather`` holding
                         ``_dev_lock`` and reaching ``refcount``'s
                         ``_lock`` is one observed pair.
- [atomic-check-act]     a guarded predicate evaluated under one lock
                         hold and acted on under a LATER hold of the
                         same lock in the same function — the decision
                         can be stale by the time it acts (the
                         watermark-check -> grant admission shape
                         ``BlockAllocator.admit_alloc`` closes).
- [blocking-under-lock]  device sync (``block_until_ready``/``.item()``),
                         jit dispatch (a call to a declared
                         ``JIT_ENTRY_POINTS`` name), ``requests.*``,
                         ``time.sleep``, ``.result()``, or ``.wait()``
                         while holding a declared lock — a scheduler
                         serialized on a blocked lock is exactly the
                         stall the TokenWeave-style overlap work cannot
                         absorb. Device classes are permitted under
                         declared ``DEVICE_LOCKS`` only.

The analysis is deliberately name-and-receiver based and statement-
ordered (the sanitize pass's philosophy): precise enough to pin the
shapes that bite, conservative enough to hold the production tree to
zero unsuppressed findings.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding
from . import lint as L

LOCKS_RULE_IDS = ("unguarded-state", "lock-order", "atomic-check-act",
                  "blocking-under-lock")

# the harness runtime is the measurement apparatus: it is not scanned by
# its own pass (the same way the graftsan runtime hooks in kv_pool are
# exercised by the dynamic tier, not the static aliasing rules)
_EXEMPT_RELPATHS = {"llm_sharding_demo_tpu/utils/graftsched.py"}

_THREAD_FACTORIES = {"Thread", "ThreadingHTTPServer", "Timer"}


# -- declarations -------------------------------------------------------------


def _module_assign(mod: L.ModuleInfo, name: str) -> Optional[ast.Assign]:
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return stmt
    return None


def declared_guarded(mod: L.ModuleInfo,
                     ) -> Tuple[Optional[Dict[str, str]], int]:
    """``GUARDED_STATE`` -> ({attr_or_prefix: lock_name}, decl line);
    (None, 0) when the module declares nothing."""
    stmt = _module_assign(mod, "GUARDED_STATE")
    if stmt is None:
        return None, 0
    if not isinstance(stmt.value, ast.Dict):
        return {}, stmt.lineno
    out: Dict[str, str] = {}
    for k, v in zip(stmt.value.keys, stmt.value.values):
        if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            out[k.value] = v.value
    return out, stmt.lineno


def declared_order(mod: L.ModuleInfo,
                   ) -> Tuple[Optional[List[str]], int]:
    """``LOCK_ORDER`` -> (ordered lock names, decl line)."""
    stmt = _module_assign(mod, "LOCK_ORDER")
    if stmt is None:
        return None, 0
    node = stmt.value
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return [], stmt.lineno
        return out, stmt.lineno
    return [], stmt.lineno


def declared_device(mod: L.ModuleInfo) -> Tuple[Optional[Set[str]], int]:
    """``DEVICE_LOCKS`` -> (names, decl line)."""
    stmt = _module_assign(mod, "DEVICE_LOCKS")
    if stmt is None:
        return None, 0
    vals = L._string_tuple(stmt.value)
    return (vals if vals is not None else set()), stmt.lineno


@dataclasses.dataclass
class LockSite:
    line: int
    name: str            # holding attribute name
    reentrant: bool
    scope: str
    foreign: bool = False  # re-wrap of ANOTHER object's lock attr
    #                        (e.g. bench instrumenting REGISTRY._lock):
    #                        the guarded-state contract lives with the
    #                        lock's OWNING module, not the wrapper


def _lock_factory(node: ast.AST) -> Optional[bool]:
    """If ``node`` constructs a lock, its reentrancy; else None.
    Recognizes ``threading.Lock/RLock/Condition()`` and the instrumented
    ``graftsched.lock/rlock(...)`` constructors (+ ``IfExp`` choosing
    between two factories)."""
    if isinstance(node, ast.IfExp):
        a, b = _lock_factory(node.body), _lock_factory(node.orelse)
        if a is None and b is None:
            return None
        return bool(a) or bool(b)
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id in ("threading", "_threading"):
            if f.attr in ("Lock", "Condition"):
                return False
            if f.attr == "RLock":
                return True
        if f.value.id == "graftsched":
            if f.attr == "lock":
                return False
            if f.attr == "rlock":
                return True
    return None


def lock_constructions(mod: L.ModuleInfo) -> List[LockSite]:
    parents = None
    out: List[LockSite] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        reentrant = _lock_factory(node.value)
        if reentrant is None:
            continue
        tgt = node.targets[0]
        foreign = False
        if isinstance(tgt, ast.Attribute):
            name = tgt.attr
            base = _dotted(tgt.value)
            foreign = base not in ("self", "cls", None)
        elif isinstance(tgt, ast.Name):
            name = tgt.id
        else:
            continue
        if parents is None:
            parents = _parents(mod.tree)
        out.append(LockSite(line=node.lineno, name=name,
                            reentrant=reentrant,
                            scope=_scope_of(node, parents, mod),
                            foreign=foreign))
    return out


def constructs_threads(mod: L.ModuleInfo) -> bool:
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Attribute, ast.Name))):
            name = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id)
            if name in _THREAD_FACTORIES:
                return True
    return False


def _parents(tree: ast.Module) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _scope_of(node: ast.AST, parents: Dict[int, ast.AST],
              mod: L.ModuleInfo) -> str:
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return mod.qualname_of.get(cur, cur.name)
        cur = parents.get(id(cur))
    return "<module>"


# -- shared context -----------------------------------------------------------


@dataclasses.dataclass
class _Ctx:
    """Everything a per-module scan needs resolved: the module's own
    guard map (+ prefix keys), the cross-module underscore guard map,
    and the global lock-name inventory."""

    own_exact: Dict[str, str]
    own_prefix: List[Tuple[str, str]]
    foreign: Dict[str, Set[str]]
    known_locks: Set[str]
    device: Set[str]
    entry_points: Set[str]
    reentrant_here: Set[str]       # reentrant constructions in THIS module
    nonreentrant_here: Set[str]

    def locks_for(self, attr: str) -> Set[str]:
        out: Set[str] = set()
        got = self.own_exact.get(attr)
        if got is not None:
            out.add(got)
        for prefix, lock_name in self.own_prefix:
            if attr.startswith(prefix):
                out.add(lock_name)
        if not out and attr.startswith("_"):
            out |= self.foreign.get(attr, set())
        return out


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted receiver key, peeling subscripts: ``self.spec`` /
    ``alloc`` / ``state.slots`` -> stable string, else None."""
    parts: List[str] = []
    while True:
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
            continue
        break
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_expr(node: ast.AST):
    """ast.walk that does not descend into nested function bodies (a
    lambda body runs later, under whatever locks its CALLER holds)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


# -- per-function scan --------------------------------------------------------


class _Region:
    __slots__ = ("base", "name", "line", "reads", "writes")

    def __init__(self, base: str, name: str, line: int):
        self.base = base
        self.name = name
        self.line = line
        self.reads: Dict[str, int] = {}
        self.writes: Dict[str, int] = {}


_BLOCKING_ATTRS = {"result": ".result() blocks on a future",
                   "wait": ".wait() blocks on an event/condition"}
_DEVICE_ATTRS = {"block_until_ready", "item"}


class _Scan:
    """One function's lock-discipline events: guarded accesses with the
    held-lock set at each, with-region sequence, observed acquisition
    pairs, blocking calls, escapes, and the call list for
    interprocedural nesting."""

    def __init__(self, mod: L.ModuleInfo, qual: str, fn: ast.AST,
                 ctx: _Ctx):
        self.mod = mod
        self.qual = qual
        self.ctx = ctx
        self.accesses: List[Tuple[int, str, str, bool]] = []
        #                  (line, base, attr, guarded)
        self.regions: List[_Region] = []
        self.pairs: List[Tuple[str, str, int, bool]] = []
        #               (outer, inner, line, same_base)
        self.blocking: List[Tuple[int, str, bool, Tuple[str, ...]]] = []
        #                 (line, what, device_class, held names)
        self.escapes: List[Tuple[int, str, str, str]] = []
        #                (line, base, attr, lock)
        # (line, trailing name, receiver base or None,
        #  held (base, name) pairs)
        self.calls: List[Tuple[int, str, Optional[str],
                               Tuple[Tuple[str, str], ...]]] = []
        self.direct_acquires: Set[str] = set()
        self._held: List[Tuple[str, str, _Region]] = []
        body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
        self._stmts(body)

    # -- statement walk --

    def _stmts(self, stmts: Sequence[ast.AST]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._with(stmt)
            elif isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self._escape_check(stmt.value, stmt.lineno)
                    self._expr(stmt.value)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._expr(stmt.test)
                self._stmts(stmt.body)
                self._stmts(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter)
                self._expr(stmt.target)
                self._stmts(stmt.body)
                self._stmts(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                self._stmts(stmt.body)
                for h in stmt.handlers:
                    self._stmts(h.body)
                self._stmts(stmt.orelse)
                self._stmts(stmt.finalbody)
            else:
                self._expr(stmt)

    def _with(self, stmt) -> None:
        taken: List[Tuple[str, str, _Region]] = []
        for item in stmt.items:
            ce = item.context_expr
            base = None
            if (isinstance(ce, ast.Attribute)
                    and ce.attr in self.ctx.known_locks):
                base = _dotted(ce.value)
            if base is not None:
                region = _Region(base, ce.attr, stmt.lineno)
                self.regions.append(region)
                self.direct_acquires.add(ce.attr)
                for ob, on, _ in self._held:
                    self.pairs.append((on, ce.attr, stmt.lineno,
                                       ob == base))
                entry = (base, ce.attr, region)
                self._held.append(entry)
                taken.append(entry)
            else:
                self._expr(ce)
            if item.optional_vars is not None:
                self._expr(item.optional_vars)
        self._stmts(stmt.body)
        for entry in taken:
            self._held.remove(entry)

    def _escape_check(self, value: ast.AST, line: int) -> None:
        if not isinstance(value, ast.Attribute):
            return
        locks = self.ctx.locks_for(value.attr)
        if not locks:
            return
        base = _dotted(value.value)
        if base is None:
            return
        for b, n, _ in self._held:
            if b == base and n in locks:
                self.escapes.append((line, base, value.attr, n))
                return

    # -- expression walk --

    def _held_names(self) -> Tuple[str, ...]:
        return tuple(n for _, n, _ in self._held)

    def _expr(self, node: ast.AST) -> None:
        for n in _walk_expr(node):
            if isinstance(n, ast.Attribute) and isinstance(
                    getattr(n, "ctx", None),
                    (ast.Load, ast.Store, ast.Del)):
                self._access(n)
            elif isinstance(n, ast.Call):
                self._call(n)

    def _access(self, node: ast.Attribute) -> None:
        locks = self.ctx.locks_for(node.attr)
        if not locks:
            return
        base = _dotted(node.value)
        if base is None:
            return
        guarded = False
        for b, name, region in self._held:
            if b == base and name in locks:
                guarded = True
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    region.writes.setdefault(node.attr, node.lineno)
                else:
                    region.reads.setdefault(node.attr, node.lineno)
        self.accesses.append((node.lineno, base, node.attr, guarded))

    def _call(self, node: ast.Call) -> None:
        f = node.func
        name = recv = None
        if isinstance(f, ast.Attribute):
            name = f.attr
            recv = _dotted(f.value)
        elif isinstance(f, ast.Name):
            name = f.id
        if name is None:
            return
        held_pairs = tuple((b, n) for b, n, _ in self._held)
        self.calls.append((node.lineno, name, recv, held_pairs))
        if held_pairs:
            what, device_class = self._blocking_kind(node, name)
            if what is not None:
                self.blocking.append((node.lineno, what, device_class,
                                      self._held_names()))

    def _blocking_kind(self, node: ast.Call,
                       name: str) -> Tuple[Optional[str], bool]:
        f = node.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            if name == "sleep" and isinstance(recv, ast.Name) \
                    and recv.id in ("time", "_time"):
                return "time.sleep()", False
            if isinstance(recv, ast.Name) and recv.id == "requests":
                return f"requests.{name}() network round trip", False
            if name == "block_until_ready":
                return "block_until_ready() device sync", True
            if name == "item" and not node.args \
                    and not isinstance(recv, ast.Constant):
                return ".item() device sync", True
            if name in _BLOCKING_ATTRS \
                    and not isinstance(recv, ast.Constant):
                return _BLOCKING_ATTRS[name], False
        if name in self.ctx.entry_points:
            return (f"jit dispatch through declared entry point "
                    f"{name!r}"), True
        return None, False


# -- driver -------------------------------------------------------------------


def _exempt_fn(qual: str) -> bool:
    leaf = qual.rpartition(".")[2]
    return leaf == "__init__" or leaf.endswith("_locked")


def _build_context(mods: Sequence[L.ModuleInfo]):
    """Global lock inventory + the cross-module underscore guard map."""
    foreign: Dict[str, Dict[str, Set[str]]] = {}
    constructed: Dict[str, Set[str]] = {}       # name -> {relpath}
    reentrant_any: Set[str] = set()
    per_mod: Dict[str, dict] = {}
    known: Set[str] = set()
    for mod in mods:
        guarded, gline = declared_guarded(mod)
        order, oline = declared_order(mod)
        device, dline = declared_device(mod)
        sites = lock_constructions(mod)
        per_mod[mod.relpath] = {
            "guarded": guarded, "gline": gline,
            "order": order, "oline": oline,
            "device": device, "dline": dline,
            "sites": sites,
        }
        for s in sites:
            constructed.setdefault(s.name, set()).add(mod.relpath)
            known.add(s.name)
            if s.reentrant:
                reentrant_any.add(s.name)
        for key, lock_name in (guarded or {}).items():
            known.add(lock_name)
            attr = key.rstrip("*")
            if attr.startswith("_") and not key.endswith("*"):
                foreign.setdefault(attr, {}).setdefault(
                    mod.relpath, set()).add(lock_name)
        known.update(order or ())
        known.update(device or ())
    return per_mod, constructed, reentrant_any, foreign, known


def run_locks(root: str, paths: Optional[List[str]] = None,
              ) -> Tuple[List[Finding], dict]:
    """The whole static pass over the production surface. ->
    (findings, summary) where summary carries ``locks_checks`` (real
    analysis units: guarded accesses resolved, regions walked, pairs
    checked, blocking calls classified — a vacuity guard on the count
    proves the rules saw the tree), ``guarded_regions`` (per-module
    count of ``with``-regions on declared locks) and ``vacuous`` (lock-
    constructing modules with ZERO guarded regions — the strict driver
    fails on these)."""
    mods: List[L.ModuleInfo] = []
    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is not None and mod.relpath not in _EXEMPT_RELPATHS:
            mods.append(mod)
    per_mod, constructed, reentrant_any, foreign_map, known = \
        _build_context(mods)

    findings: List[Finding] = []
    checks = 0
    guarded_regions: Dict[str, int] = {}
    vacuous: List[str] = []
    # global observed order pairs: (outer, inner) -> "path:line (scope)"
    observed: Dict[Tuple[str, str], str] = {}
    inversion_reported: Set[frozenset] = set()

    for mod in mods:
        info = per_mod[mod.relpath]
        guarded, order, device = (info["guarded"], info["order"],
                                  info["device"])
        sites: List[LockSite] = info["sites"]
        declared_lock_names = set((guarded or {}).values()) | set(
            device or ())

        # -- declaration consistency (rides unguarded-state) --
        # foreign sites (re-wraps of another object's lock attr, e.g.
        # bench instrumenting REGISTRY._lock) answer to the lock's
        # OWNING module's declarations, not this module's
        own_sites = [s for s in sites if not s.foreign]
        if own_sites and guarded is None:
            findings.append(Finding(
                "unguarded-state", mod.relpath, own_sites[0].line,
                own_sites[0].scope,
                f"threaded module constructs lock "
                f"{own_sites[0].name!r} but declares no GUARDED_STATE "
                "— the locks pass cannot hold it to any contract "
                "(declare the state each lock guards, or DEVICE_LOCKS "
                "for pure serialization locks)"))
        for s in own_sites:
            if guarded is not None and s.name not in declared_lock_names:
                findings.append(Finding(
                    "unguarded-state", mod.relpath, s.line, s.scope,
                    f"lock {s.name!r} is constructed but guards no "
                    "declared state (add its attrs to GUARDED_STATE, or "
                    "the name to DEVICE_LOCKS if its job is serializing "
                    "device work)"))
        for name in sorted(set((guarded or {}).values())):
            if name not in constructed:
                findings.append(Finding(
                    "unguarded-state", mod.relpath, info["gline"] or 1,
                    "<module>",
                    f"GUARDED_STATE names lock {name!r} but no scanned "
                    "module constructs it (stale declaration)"))
        for name in sorted(set(order or ())):
            if name not in constructed:
                findings.append(Finding(
                    "lock-order", mod.relpath, info["oline"] or 1,
                    "<module>",
                    f"LOCK_ORDER names lock {name!r} but no scanned "
                    "module constructs it (stale declaration)"))
        for name in sorted(set(device or ())):
            if name not in constructed:
                findings.append(Finding(
                    "blocking-under-lock", mod.relpath,
                    info["dline"] or 1, "<module>",
                    f"DEVICE_LOCKS names lock {name!r} but no scanned "
                    "module constructs it (stale declaration)"))

        ctx = _Ctx(
            own_exact={k: v for k, v in (guarded or {}).items()
                       if not k.endswith("*")},
            own_prefix=[(k[:-1], v) for k, v in (guarded or {}).items()
                        if k.endswith("*")],
            foreign={attr: set().union(*(lk for rel, lk in by.items()
                                         if rel != mod.relpath))
                     for attr, by in foreign_map.items()
                     if any(rel != mod.relpath for rel in by)},
            known_locks=known,
            device=set(device or ()),
            entry_points=set(mod.declared_entry_points),
            reentrant_here={s.name for s in sites if s.reentrant},
            nonreentrant_here={s.name for s in sites if not s.reentrant},
        )

        scans: Dict[str, _Scan] = {}
        region_count = 0
        for qual, fn in sorted(mod.functions.items()):
            scan = _Scan(mod, qual, fn, ctx)
            scans[qual] = scan
            checks += (1 + len(scan.accesses) + len(scan.regions)
                       + len(scan.pairs) + len(scan.blocking))
            region_count += sum(1 for r in scan.regions
                                if r.name in declared_lock_names)

            exempt = _exempt_fn(qual)
            # unguarded-state: accesses outside a matching hold
            if not exempt:
                reported: Set[Tuple[int, str]] = set()
                for line, base, attr, ok in scan.accesses:
                    if ok or (line, attr) in reported:
                        continue
                    reported.add((line, attr))
                    locks = sorted(ctx.locks_for(attr))
                    findings.append(Finding(
                        "unguarded-state", mod.relpath, line, qual,
                        f"{base}.{attr} is declared guarded by "
                        f"{locks[0]!r} but is touched with no matching "
                        f"`with {base}.{locks[0]}` hold — a concurrent "
                        "writer can interleave (take the lock, or route "
                        "through a *_locked helper whose caller holds "
                        "it)"))
                for line, base, attr, lock_name in scan.escapes:
                    findings.append(Finding(
                        "unguarded-state", mod.relpath, line, qual,
                        f"guarded state {base}.{attr} escapes its "
                        f"{lock_name!r} region via return — the caller "
                        "reads/mutates it after the lock is released "
                        "(return a copy/snapshot instead)"))

            # atomic-check-act: read-only hold, then a later acting hold
            by_lock: Dict[Tuple[str, str], List[_Region]] = {}
            for r in scan.regions:
                by_lock.setdefault((r.base, r.name), []).append(r)
            for (base, name), regions in by_lock.items():
                for i, ri in enumerate(regions):
                    if not ri.reads or ri.writes:
                        continue
                    for rj in regions[i + 1:]:
                        acted = sorted(set(rj.writes) & set(ri.reads))
                        if acted:
                            findings.append(Finding(
                                "atomic-check-act", mod.relpath,
                                rj.line, qual,
                                f"guarded {acted[0]!r} is tested under "
                                f"the {name!r} hold at line {ri.line} "
                                "but acted on under this separate "
                                "later hold — the predicate can be "
                                "stale by the time it acts (merge the "
                                "holds or re-validate before acting)"))
                            break

            # blocking-under-lock
            for line, what, device_class, held in scan.blocking:
                offending = [h for h in held
                             if not (device_class and h in ctx.device)]
                if not offending:
                    continue
                findings.append(Finding(
                    "blocking-under-lock", mod.relpath, line, qual,
                    f"{what} while holding {offending[0]!r} — every "
                    "thread contending this lock stalls behind the "
                    "blocked holder (move the blocking work outside "
                    "the hold"
                    + ("" if device_class else
                       "; DEVICE_LOCKS does not exempt host blocking")
                    + ")"))

        # -- interprocedural lock nesting --
        suffix = L._suffix_index(mod)
        direct: Dict[str, Set[str]] = {
            q: set(s.direct_acquires) for q, s in scans.items()}
        callees: Dict[str, Set[str]] = {}
        for q, s in scans.items():
            outs = set()
            for _, name, _, _ in s.calls:
                hit = suffix.get(name)
                if hit is not None:
                    outs.add(hit[0])
            callees[q] = outs
        trans = {q: set(d) for q, d in direct.items()}
        for _ in range(len(trans)):
            changed = False
            for q in trans:
                for c in callees.get(q, ()):
                    add = trans.get(c, set()) - trans[q]
                    if add:
                        trans[q] |= add
                        changed = True
            if not changed:
                break

        pair_sites: Dict[Tuple[str, str], Tuple[int, str, bool]] = {}
        for q, s in scans.items():
            for outer, inner, line, same_base in s.pairs:
                pair_sites.setdefault((outer, inner),
                                      (line, q, same_base))
            for line, name, recv, held in s.calls:
                if not held:
                    continue
                hit = suffix.get(name)
                if hit is None:
                    continue
                for inner in trans.get(hit[0], ()):
                    for outer_base, outer in held:
                        # a call on the SAME receiver the outer lock is
                        # held on re-enters that instance's locks (the
                        # self-call reentrancy shape)
                        same = recv is not None and recv == outer_base
                        pair_sites.setdefault((outer, inner),
                                              (line, q, same))
        checks += len(pair_sites)

        order_idx = {name: i for i, name in enumerate(order or ())}
        for (outer, inner), (line, q, same_base) in sorted(
                pair_sites.items()):
            site = f"{mod.relpath}:{line} ({q})"
            if outer == inner:
                if (same_base and outer in ctx.nonreentrant_here
                        and outer not in ctx.reentrant_here):
                    findings.append(Finding(
                        "lock-order", mod.relpath, line, q,
                        f"non-reentrant lock {outer!r} re-acquired on a "
                        "path that already holds it — self-deadlock "
                        "(make it an RLock or split the inner scope "
                        "out)"))
                continue
            if outer in order_idx and inner in order_idx \
                    and order_idx[outer] > order_idx[inner]:
                findings.append(Finding(
                    "lock-order", mod.relpath, line, q,
                    f"{inner!r} acquired while holding {outer!r}, but "
                    f"this module's LOCK_ORDER is {tuple(order)} — an "
                    "opposite-order path deadlocks under contention"))
            prev = observed.get((outer, inner))
            if prev is None:
                observed[(outer, inner)] = site
            rev = observed.get((inner, outer))
            key = frozenset((outer, inner))
            if rev is not None and key not in inversion_reported:
                inversion_reported.add(key)
                findings.append(Finding(
                    "lock-order", mod.relpath, line, q,
                    f"inconsistent acquisition order: {inner!r} taken "
                    f"while holding {outer!r} here, but the opposite "
                    f"order is taken at {rev} — two contending threads "
                    "deadlock"))

        if own_sites or (guarded is not None and guarded):
            guarded_regions[mod.relpath] = region_count
            if own_sites and region_count == 0:
                vacuous.append(mod.relpath)

    summary = {
        "locks_checks": checks,
        "guarded_regions": guarded_regions,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
