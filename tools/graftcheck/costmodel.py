"""graftplan: compile-free cost model + auto-sharding planner.

ROADMAP item 5 ("Learning to Shard" lite): the verifier machinery built
by PR 3/PR 5 — abstract eval of every partition plan against an
``AbstractMesh``, exact per-entry-point program counts through the
engine's own planners — refactored from a *gate* into a
*decision-maker*. For a model family x mesh x traffic mix, the planner
enumerates serving candidates (partition plan x stage split x batch
mode x max_batch x KV-pool geometry), gates each through the EXISTING
semantic verifier (invalid plans are rejected with the verifier's own
diagnostics and never scored), scores the survivors compile-free, and
emits a ranked table plus one chosen config that ``serving/app.py``
consumes via ``AUTO_PLAN=1``.

Cost model — the Helix Parallelism framing (PAPERS.md): at interactive
batch sizes DECODE is bound by *bytes moved* — weight and KV-cache HBM
streams plus inter-chip collective traffic — not FLOPs. Every term is
derived statically:

- **comm bytes** are read off traced jaxprs (``jax.make_jaxpr`` over
  ``AbstractMesh`` stand-ins — zero devices, zero compile): walk the
  program the topology would run, sum collective operand avals by the
  per-primitive formulas below, multiply by scan trip counts. The
  pipelined (pp) program is THE real ``PipelinedDecoder._pp_blocks``
  step (``semantic.build_ppdecode_programs``); tp/ep use declared
  stand-in programs carrying the documented Megatron / expert-dispatch
  collective schedules at real avals (GSPMD inserts the actual
  collectives at compile time, which a compile-free pass never sees —
  the stand-ins make the schedule explicit and walkable).
- **HBM footprint** from avals: params via ``jax.eval_shape`` over
  ``init_params`` divided by the derived sharding (``derive_pspecs``
  from each family's ``SHARDING_DESCRIPTOR`` — zero hand-written
  PartitionSpecs), KV state via the pool geometry math
  (``ops.paged_attention.pool_shape``) or the contiguous cache aval,
  peak activations as the largest single intermediate in the traced
  decode-step jaxpr. Exactness is pinned against real CPU buffer
  ``nbytes`` by tests/test_graftplan.py.
- **program counts** via the existing ``recompile.certify`` /
  ``certify_paged`` machinery (exact — certified equal to observed jit
  cache sizes — for admission-mode and solo-paged candidates; rows
  where the count is a static upper bound carry
  ``programs_exact: false``).

Collective byte formulas (TOTAL bytes crossing links, per execution of
the traced program; operand avals are the per-device view inside
``shard_map``):

- ``ppermute``:        operand_bytes x n_pairs (each pair ships one
                       per-device operand along one link)
- ``psum``/``pmax``/``pmin``: 2 x operand_bytes x (n - 1)
                       (bidirectional ring all-reduce)
- ``all_gather``:      operand_bytes x n x (n - 1) (every device
                       receives the other n-1 shards)
- ``reduce_scatter``:  operand_bytes x (n - 1)
- ``all_to_all``:      operand_bytes x (n - 1) (each device keeps 1/n
                       of its operand local)

Nested ``scan`` bodies multiply by the trip count; ``while`` bodies
count once (a static bound cannot know the trip count — documented);
``cond`` takes the max over branches.

Ranking: infeasible rows (HBM over budget) and verifier-rejected rows
never rank. Feasible rows sort by modeled decode cost per token
(weight-stream bytes per device amortized over the effective batch +
KV-stream bytes + paged gather/scatter amortization + ICI-weighted comm
bytes), tie-broken by fewer compiled programs, smaller HBM footprint,
then config simplicity (contiguous before paged, admission before iter,
smaller max_batch, fewer stages) — so on a single chip with
single-stream traffic the planner reproduces the hand-tuned serving
default by construction, and the choice only moves when the cost model
finds real bytes to save.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .core import Finding

_APP_PATH = "llm_sharding_demo_tpu/serving/app.py"

# relative cost of moving one byte over ICI vs streaming it from HBM
# (decode-step granularity; a single scalar keeps the model inspectable
# — the ranking rules in docs/ARCHITECTURE.md "Planning" discuss it)
ICI_BYTE_WEIGHT = 4.0
# the iteration scheduler's default segment width: paged decode pays one
# gather + one scatter of the row cache per segment
PAGED_SEG_STEPS = 32
DEFAULT_HBM_GB = 16.0


# -- traffic -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TrafficRow:
    """One request shape class in the traffic mix: ``count`` concurrent
    requests of ``prompt_len`` prompt tokens decoding ``max_new``."""

    prompt_len: int
    max_new: int
    count: int = 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


DEFAULT_TRAFFIC: Tuple[TrafficRow, ...] = (TrafficRow(16, 32, 1),)


def parse_traffic(spec: str) -> Tuple[TrafficRow, ...]:
    """``"16/32x8,64/16"`` -> 8 concurrent 16-prompt/32-new requests
    plus one 64-prompt/16-new request. Elements are
    ``prompt/new[xcount]``, comma-separated."""
    rows: List[TrafficRow] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        shape, _, cnt = part.partition("x")
        p, sep, n = shape.partition("/")
        try:
            row = TrafficRow(int(p), int(n) if sep else 0,
                             int(cnt) if cnt else 1)
        except ValueError as e:
            raise ValueError(
                f"bad traffic element {part!r}: want prompt/new[xcount], "
                f"e.g. 16/32x8") from e
        if row.prompt_len < 1 or row.max_new < 1 or row.count < 1:
            raise ValueError(
                f"bad traffic element {part!r}: prompt/new/count must "
                "all be >= 1")
        rows.append(row)
    if not rows:
        raise ValueError(f"traffic spec {spec!r} names no request shapes")
    return tuple(rows)


def concurrency(traffic: Sequence[TrafficRow]) -> int:
    return sum(r.count for r in traffic)


# -- derived sharding (zero hand-written PartitionSpecs) ---------------------


@functools.lru_cache(maxsize=64)
def param_avals(module, config):
    """Aval tree of the family's params. Memoized: one plan() run calls
    this per candidate (gating, sharding derivation, scoring) with the
    same (module, config) — configs are frozen dataclasses, so identity
    caching is sound, and callers never mutate the aval tree."""
    import jax
    return jax.eval_shape(lambda k: module.init_params(config, k),
                          jax.random.PRNGKey(0))


def derive_pspecs(module, config, mesh_axes: Dict[str, int]):
    """PartitionSpec tree derived from the family's
    ``SHARDING_DESCRIPTOR`` — architectural facts (which ops are
    Megatron column/row, which are expert-stacked), not hand-written
    specs. Pinned equal to the hand-tuned ``parallel.spmd`` layouts for
    all three families by tests/test_graftplan.py, which is what lets
    the planner onboard new families from their descriptors alone.

    Size-1 axes derive no sharding (replication already); ``config`` is
    unused by the tree shape but kept in the signature because the
    descriptor's divisor fields are validated against it by
    ``gate_candidate``."""
    from jax.sharding import PartitionSpec as P
    desc = getattr(module, "SHARDING_DESCRIPTOR", None)
    if desc is None:
        raise ValueError(
            f"{module.__name__} declares no SHARDING_DESCRIPTOR — the "
            "planner cannot derive a sharding for this family")
    tp = "tp" if mesh_axes.get("tp", 0) > 1 else None
    ep = "ep" if mesh_axes.get("ep", 0) > 1 else None
    avals = param_avals(module, config)

    def leaf_spec(path: str, rank: int):
        if not path.startswith("blocks."):
            return P()
        op, _, leaf = path.rpartition(".")
        entries = [None] * rank
        if ep and op in desc["expert"]:
            entries[1] = ep          # [L, E, ...]: the expert axis
        if tp and op in desc["column"]:
            entries[-1] = tp         # output dim (kernel AND bias)
        elif tp and op in desc["row"] and leaf == "kernel":
            entries[-2] = tp         # input dim; row bias replicates
        return P(*entries)

    def build(node, path: str):
        if isinstance(node, dict):
            return {k: build(v, f"{path}.{k}" if path else k)
                    for k, v in node.items()}
        return leaf_spec(path, len(node.shape))

    return build(avals, "")


def _leaf_items(tree, prefix: str = ""):
    if isinstance(tree, dict):
        for k in tree:
            yield from _leaf_items(tree[k], f"{prefix}.{k}" if prefix else k)
    else:
        yield prefix, tree


def tree_bytes(avals) -> int:
    return sum(int(np.prod(a.shape, dtype=np.int64))
               * np.dtype(a.dtype).itemsize
               for _, a in _leaf_items(avals))


def per_device_param_bytes(avals, pspecs, mesh_axes: Dict[str, int]) -> int:
    """One device's share of the param bytes under a derived spec tree
    (a leaf sharded over an axis holds 1/size of its bytes)."""
    specs = dict(_leaf_items(pspecs))
    total = 0
    for path, aval in _leaf_items(avals):
        nbytes = (int(np.prod(aval.shape, dtype=np.int64))
                  * np.dtype(aval.dtype).itemsize)
        shards = 1
        for entry in specs[path]:
            for axis in (entry if isinstance(entry, tuple) else (entry,)):
                if axis is not None:
                    shards *= mesh_axes.get(axis, 1)
        total += math.ceil(nbytes / shards)
    return total


# -- HBM footprint -----------------------------------------------------------


def kv_cache_bytes(config, batch: int, max_seq: int,
                   dtype_bytes: int = 4) -> int:
    """Contiguous KV state for ``batch`` rows: the
    ``[L, B, Hkv, max_seq, hd]`` k/v pair the engine allocates."""
    heads = getattr(config, "n_kv_head", config.n_head)
    return (2 * config.n_layer * batch * heads * max_seq
            * config.head_dim * dtype_bytes)


def kv_pool_bytes(config, num_blocks: int, block_size: int,
                  dtype_bytes: int = 4) -> int:
    """The paged pool's one fixed buffer — THE ``kv_pool`` geometry math
    (``ops.paged_attention.pool_shape``, trash block included), so the
    planner and the allocator can never disagree about pool bytes."""
    from llm_sharding_demo_tpu.ops.paged_attention import pool_shape
    heads = getattr(config, "n_kv_head", config.n_head)
    shape = pool_shape(config.n_layer, num_blocks, heads, block_size,
                       config.head_dim)
    return int(np.prod(shape, dtype=np.int64)) * dtype_bytes


@functools.lru_cache(maxsize=64)
def peak_activation_bytes(module, config, batch: int, max_seq: int) -> int:
    """Largest single intermediate in the traced decode-step jaxpr
    (``forward_with_cache`` at S=1 over the family's real cache aval) —
    the working-set spike on top of params + KV. Memoized (the full
    forward trace is the planner's most expensive step, and every
    candidate at the same effective batch shares it)."""
    import jax
    import jax.numpy as jnp
    pavals = param_avals(module, config)
    cache = jax.eval_shape(
        lambda: module.make_cache(config, batch, max_seq))
    ids = jax.ShapeDtypeStruct((batch, 1), jnp.int32)
    jaxpr = jax.make_jaxpr(
        lambda p, i, c: module.forward_with_cache(p, i, config, c))(
            pavals, ids, cache)

    peak = 0

    def walk(jxp):
        nonlocal peak
        from .semantic import _sub_jaxprs
        for eqn in jxp.eqns:
            out = sum(int(np.prod(v.aval.shape, dtype=np.int64))
                      * np.dtype(v.aval.dtype).itemsize
                      for v in eqn.outvars if hasattr(v, "aval"))
            peak = max(peak, out)
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr.jaxpr)
    return peak


# -- comm bytes off traced jaxprs --------------------------------------------


def _axis_size(eqn, mesh_axes: Dict[str, int]) -> int:
    # reduction collectives (psum/pmax/pmin) carry ``axes``; the data
    # movers (ppermute/all_gather/all_to_all) carry ``axis_name``
    names = eqn.params.get("axis_name", eqn.params.get("axes", ()))
    if not isinstance(names, (tuple, list)):
        names = (names,)
    size = 1
    for n in names:
        size *= mesh_axes.get(n, 1)
    return size


def _operand_bytes(eqn) -> int:
    from jax.core import Literal
    total = 0
    for v in eqn.invars:
        if isinstance(v, Literal) or not hasattr(v, "aval"):
            continue
        if not hasattr(v.aval, "shape"):
            continue
        total += (int(np.prod(v.aval.shape, dtype=np.int64))
                  * np.dtype(v.aval.dtype).itemsize)
    return total


def collective_bytes(jaxpr, mesh_axes: Dict[str, int]) -> int:
    """Total collective bytes one execution of ``jaxpr`` moves, by the
    per-primitive formulas in the module docstring. Recurses into
    sub-jaxprs; ``scan`` multiplies by trip count, ``cond`` takes the
    max branch, ``while`` counts one iteration."""
    from .semantic import COMM_PRIMITIVES

    def eqn_bytes(eqn) -> int:
        name = eqn.primitive.name
        if name not in COMM_PRIMITIVES:
            return 0
        n = _axis_size(eqn, mesh_axes)
        if n <= 1 and name != "ppermute":
            return 0
        b = _operand_bytes(eqn)
        if name == "ppermute":
            return b * len(eqn.params.get("perm", ()))
        if name in ("psum", "pmax", "pmin"):
            return 2 * b * (n - 1)
        if name == "all_gather":
            return b * n * (n - 1)
        if name in ("reduce_scatter", "all_to_all"):
            return b * (n - 1)
        return 0

    def walk(jxp) -> int:
        total = 0
        for eqn in jxp.eqns:
            total += eqn_bytes(eqn)
            name = eqn.primitive.name
            if name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                total += eqn.params["length"] * walk(body)
            elif name == "cond":
                total += max((walk(b.jaxpr)
                              for b in eqn.params["branches"]), default=0)
            elif name == "while":
                total += (walk(eqn.params["cond_jaxpr"].jaxpr)
                          + walk(eqn.params["body_jaxpr"].jaxpr))
            else:
                from .semantic import _sub_jaxprs
                for sub in _sub_jaxprs(eqn):
                    total += walk(sub)
        return total

    return walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)


def comm_bytes_program(fn, args, mesh_axes: Dict[str, int]) -> int:
    import jax
    return collective_bytes(jax.make_jaxpr(fn)(*args), mesh_axes)


# -- topology collective-schedule programs -----------------------------------


def pp_decode_step_program(n_stages: int, batch: int = 1,
                           module=None, config=None, mesh=None) -> tuple:
    """THE pp decode-step (fn, args) selection off
    ``semantic.build_ppdecode_programs`` — shared by the cost model's
    byte walk and bench.py's ICI calibration row (which compiles the
    same step on a concrete mesh), so the program being priced and the
    program being measured cannot drift apart."""
    from . import semantic
    rows = [r for r in semantic.build_ppdecode_programs(
        n_stages, batch=batch, module=module, config=config, mesh=mesh)
        if r[0].endswith("decode-step")]
    (_label, _scope, fn, args), = rows
    return fn, args


def pp_decode_comm_bytes(n_stages: int, batch: int = 1,
                         module=None, config=None) -> int:
    """Comm bytes of ONE pipelined decode token: the real
    ``PipelinedDecoder._pp_blocks`` step traced at S=1 (see
    ``semantic.build_ppdecode_programs`` — the same program the overlap
    lint walks). ``module``/``config`` are the model actually being
    scored (omitted: the registry gpt2 stand-in) — the handoff bytes
    scale with THAT model's hidden width, so pricing the stand-in
    would bias pp against tp/ep on any real config."""
    fn, args = pp_decode_step_program(n_stages, batch=batch,
                                      module=module, config=config)
    return comm_bytes_program(fn, args, {"pp": n_stages})


def tp_decode_comm_bytes(config, batch: int, tp: int) -> int:
    """Comm bytes of one tensor-parallel decode token: the Megatron
    collective schedule — per block, one psum of the [B, 1, D]
    activations after the row-parallel attention projection and one
    after the row-parallel MLP down projection — traced as a shard_map
    stand-in at real avals over an ``AbstractMesh`` and walked like any
    other program. (GSPMD inserts the real collectives at compile time;
    the stand-in declares the schedule the annotation provably
    produces.)"""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P

    d = config.n_embd
    hidden = getattr(config, "intermediate_size", 4 * d)
    l = config.n_layer
    attn_sh = max(d // tp, 1)
    mlp_sh = max(hidden // tp, 1)
    mesh = AbstractMesh((("tp", tp),))

    def per_device(h, wcol_a, wrow_a, wcol_m, wrow_m):
        # weight args are already the per-device shards ([in, out/tp] /
        # [in/tp, out] per layer, stacked over L); h is replicated
        def body(carry, ws):
            h = carry
            ca, ra, cm, rm = ws
            a = jnp.einsum("bsd,df->bsf", h, ca)          # column partial
            h = h + jax.lax.psum(
                jnp.einsum("bsf,fd->bsd", a, ra), "tp")   # row + psum
            m = jnp.einsum("bsd,df->bsf", h, cm)
            h = h + jax.lax.psum(
                jnp.einsum("bsf,fd->bsd", m, rm), "tp")
            return h, None
        h, _ = jax.lax.scan(body, h, (wcol_a, wrow_a, wcol_m, wrow_m))
        return h

    from llm_sharding_demo_tpu.parallel._shard_compat import shard_map
    rep = P()
    fn = shard_map(per_device, mesh=mesh, in_specs=(rep,) * 5,
                   out_specs=rep, axis_names={"tp"})
    h = jax.ShapeDtypeStruct((batch, 1, d), jnp.float32)
    args = (h,
            jax.ShapeDtypeStruct((l, d, attn_sh), jnp.float32),
            jax.ShapeDtypeStruct((l, attn_sh, d), jnp.float32),
            jax.ShapeDtypeStruct((l, d, mlp_sh), jnp.float32),
            jax.ShapeDtypeStruct((l, mlp_sh, d), jnp.float32))
    return comm_bytes_program(fn, args, {"tp": tp})


def kvp_decode_comm_bytes(config, batch: int, kvp: int) -> int:
    """Comm bytes of one decode token with the paged pool's kv-head
    plane sharded over ``kvp``: each device attends the (replicated)
    query against only its resident kv shard — a flash-style PARTIAL
    softmax (un-normalized o plus log-sum-exp per query head) — then
    the partials cross the kvp axis once per block (all_gather of
    ``o [B, Hq, hd]`` f32 + ``lse [B, Hq]`` f32) and combine with the
    usual max/exp renormalization. Traced as a shard_map stand-in at
    real avals over an ``AbstractMesh`` and walked like any other
    program (the tp/ep rationale: the stand-in declares the schedule
    the pool-plane sharding provably produces)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P

    hq = config.n_head
    hd = config.head_dim
    mesh = AbstractMesh((("kvp", kvp),))

    def per_device(o_part, lse_part):
        def body(carry, _):
            o, lse = carry
            o_all = jax.lax.all_gather(o, "kvp")       # [kvp, B, Hq, hd]
            lse_all = jax.lax.all_gather(lse, "kvp")   # [kvp, B, Hq]
            m = jnp.max(lse_all, axis=0)
            w = jnp.exp(lse_all - m[None])
            norm = jnp.sum(w, axis=0)
            o = jnp.sum(o_all * w[..., None], axis=0) / norm[..., None]
            lse = m + jnp.log(norm)
            return (o, lse), None
        (o, _), _ = jax.lax.scan(body, (o_part, lse_part), None,
                                 length=config.n_layer)
        return o

    from llm_sharding_demo_tpu.parallel._shard_compat import shard_map
    rep = P()
    fn = shard_map(per_device, mesh=mesh, in_specs=(rep, rep),
                   out_specs=rep, axis_names={"kvp"})
    o = jax.ShapeDtypeStruct((batch, hq, hd), jnp.float32)
    lse = jax.ShapeDtypeStruct((batch, hq), jnp.float32)
    return comm_bytes_program(fn, (o, lse), {"kvp": kvp})


def ep_decode_comm_bytes(config, batch: int, ep: int) -> int:
    """Comm bytes of one expert-parallel decode token: the expert
    dispatch/combine all-to-alls GSPMD derives from the expert-axis
    sharding — per block, the dispatched activations ``[E, B, C, D]``
    cross the ep axis twice. Traced as a shard_map stand-in (same
    rationale as the tp schedule)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P
    from llm_sharding_demo_tpu.models.moe import expert_capacity

    e = config.n_experts
    d = config.n_embd
    cap = expert_capacity(config, 1)
    mesh = AbstractMesh((("ep", ep),))
    # per-device dispatched view, flattened so the exchanged axis is
    # exactly the ep axis: [ep, (E/ep)*B*C, D]
    rows = max(1, (e // ep) * batch * cap)

    def per_device(x):
        def body(carry, _):
            x = carry
            y = jax.lax.all_to_all(x, "ep", split_axis=0, concat_axis=0)
            x = jax.lax.all_to_all(y, "ep", split_axis=0, concat_axis=0)
            return x, None
        x, _ = jax.lax.scan(body, x, None, length=config.n_layer)
        return x

    from llm_sharding_demo_tpu.parallel._shard_compat import shard_map
    fn = shard_map(per_device, mesh=mesh, in_specs=(P("ep"),),
                   out_specs=P("ep"), axis_names={"ep"})
    x = jax.ShapeDtypeStruct((ep * ep, rows, d), jnp.float32)
    return comm_bytes_program(fn, (x,), {"ep": ep})


# -- candidates --------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One serving configuration the planner scores — exactly the knobs
    ``utils.config.ServingConfig`` exposes, so a chosen candidate maps
    1:1 onto env vars / an AUTO_PLAN override."""

    topology: str = "single"          # single | pp | tp | ep | kvp | kvp-tp
    boundaries: Tuple[int, ...] = ()  # pp stage split (interior bounds)
    batch_mode: str = "admission"
    max_batch: int = 1
    kv_pool_blocks: int = 0
    kv_block_size: int = 16

    @property
    def n_stages(self) -> int:
        return len(self.boundaries) + 1 if self.topology == "pp" else 1

    def label(self) -> str:
        parts = [self.topology]
        if self.topology == "pp":
            parts.append("b" + "+".join(str(b) for b in self.boundaries))
        parts.append(self.batch_mode)
        parts.append(f"mb{self.max_batch}")
        if self.kv_pool_blocks:
            parts.append(f"kv{self.kv_pool_blocks}x{self.kv_block_size}")
        return "/".join(parts)

    def serving_env(self) -> Dict[str, str]:
        """The env-var view of this candidate (the planner quickstart's
        copy-paste output; AUTO_PLAN applies the same mapping
        in-process)."""
        env = {
            "BATCH_MODE": self.batch_mode,
            "MAX_BATCH": str(self.max_batch),
            "PP_DECODE": "1" if self.topology == "pp" else "0",
            "TP_DECODE": "1" if self.topology in ("tp", "kvp-tp") else "0",
            "EP_DECODE": "1" if self.topology == "ep" else "0",
            "KVP_DECODE": "1" if self.topology in ("kvp", "kvp-tp")
                          else "0",
            "KV_POOL_BLOCKS": str(self.kv_pool_blocks),
            "KV_BLOCK_SIZE": str(self.kv_block_size),
        }
        if self.topology == "pp":
            env["BOUNDARIES"] = ",".join(str(b) for b in self.boundaries)
        return env


def enumerate_candidates(module, config, mesh_axes: Dict[str, int],
                         max_seq: int, max_batch_cap: int = 8,
                         kv_pool_blocks: int = 0,
                         kv_block_size: int = 16,
                         include_unsharded: bool = True,
                         ) -> List[Candidate]:
    """The candidate space: every topology the mesh and family admit x
    batch modes x batch widths x pool geometries. Composition legality
    is NOT decided here — ``gate_candidate`` rejects with diagnostics,
    so an illegal combination shows up as a rejected row rather than
    silently missing. ``include_unsharded=False`` drops the single
    rows (``plan_for_serving`` scores them once, on the no-mesh pass,
    instead of once per candidate mesh)."""
    from llm_sharding_demo_tpu.models import is_stage_partitionable
    from llm_sharding_demo_tpu.parallel import partition as Pt

    topos: List[Tuple[str, Tuple[int, ...]]] = (
        [("single", ())] if include_unsharded else [])
    if mesh_axes.get("pp", 0) > 1 and is_stage_partitionable(config) \
            and mesh_axes["pp"] <= config.n_layer:
        topos.append(("pp", tuple(Pt.balanced_boundaries(
            config.n_layer, mesh_axes["pp"]))))
    if mesh_axes.get("tp", 0) > 1 and not hasattr(config, "n_experts"):
        topos.append(("tp", ()))
    if mesh_axes.get("ep", 0) > 1 and hasattr(config, "n_experts"):
        topos.append(("ep", ()))

    widths = sorted({1, max(1, max_batch_cap)})
    out: List[Candidate] = []
    for topo, bounds in topos:
        for mb in widths:
            out.append(Candidate(topo, bounds, "admission", mb))
            if mb > 1 and topo == "single":
                out.append(Candidate(topo, bounds, "iter", mb))
            if kv_pool_blocks and topo == "single":
                mode = "iter" if mb > 1 else "admission"
                out.append(Candidate(topo, bounds, mode, mb,
                                     kv_pool_blocks, kv_block_size))
    # kvp: the paged pool's kv-head plane sharded over its own mesh axis
    # (multi-axis rows — kvp alone with replicated params, or kvp x tp
    # with the descriptor-derived param sharding on top). There is
    # nothing to shard without a pool, and the pool composes at
    # MAX_BATCH=1 admission outside the iter loop, so these rows carry
    # exactly that shape; divisibility/pspec legality is gate_candidate's
    # job as always (an indivisible kv-head count shows up as a rejected
    # row with diagnostics, not a missing one).
    if mesh_axes.get("kvp", 0) > 1 and kv_pool_blocks:
        out.append(Candidate("kvp", (), "admission", 1,
                             kv_pool_blocks, kv_block_size))
        if mesh_axes.get("tp", 0) > 1 and not hasattr(config, "n_experts"):
            out.append(Candidate("kvp-tp", (), "admission", 1,
                                 kv_pool_blocks, kv_block_size))
    return out


# -- gate: the existing semantic verifier ------------------------------------


def gate_candidate(module, config, cand: Candidate,
                   mesh_axes: Dict[str, int], max_seq: int,
                   ) -> Tuple[List[Finding], Optional[dict]]:
    """Every check the verifier already owns, plus the serving layer's
    own composition guards, run statically. Non-empty findings =
    rejected (never scored), with the same diagnostics ``python -m
    tools.graftcheck`` would print. Returns ``(findings, pspecs)`` —
    ``pspecs`` is the derived sharding tree for tp/ep candidates."""
    from . import semantic
    where = cand.label()
    findings: List[Finding] = []

    def guard(ok: bool, msg: str):
        if not ok:
            findings.append(Finding("plan-gate", _APP_PATH, 1, where, msg))

    # serving composition rules (mirrors serving/app.py's startup guards)
    guard(cand.batch_mode != "iter" or cand.max_batch > 1,
          "BATCH_MODE=iter requires MAX_BATCH > 1")
    from llm_sharding_demo_tpu.models import is_window_independent
    if cand.batch_mode == "iter" or cand.kv_pool_blocks:
        guard(is_window_independent(config),
              f"{type(config).__name__} is window-dependent (capacity "
              "routing); iter scheduling / paged KV serve dense families")
    if cand.kv_pool_blocks:
        guard(cand.topology in ("single", "kvp", "kvp-tp"),
              "KV_POOL_BLOCKS drives the paged engine's storage (single "
              "or kvp-sharded pool planes); PP/EP/TP_DECODE keep "
              "contiguous caches")
        guard(cand.max_batch == 1 or cand.batch_mode == "iter",
              "KV_POOL_BLOCKS batches through BATCH_MODE=iter")
        guard(max_seq % cand.kv_block_size == 0,
              f"MAX_SEQ={max_seq} must be a multiple of KV_BLOCK_SIZE="
              f"{cand.kv_block_size}")
    if cand.batch_mode == "iter":
        guard(cand.topology == "single",
              "BATCH_MODE=iter drives the single-device engine's segment "
              "loop; PP/EP/TP_DECODE use BATCH_MODE=admission")
    desc = getattr(module, "SHARDING_DESCRIPTOR", {})
    if cand.topology == "tp":
        tp = mesh_axes.get("tp", 1)
        for field in desc.get("tp_divisors", ()):
            v = getattr(config, field)
            guard(v % tp == 0,
                  f"TP_DECODE: {field}={v} not divisible by the "
                  f"{tp}-device tp axis (attention shards whole heads)")
    if cand.topology == "ep":
        ep = mesh_axes.get("ep", 1)
        for field in desc.get("ep_divisors", ()):
            v = getattr(config, field)
            guard(v % ep == 0,
                  f"EP_DECODE: {field}={v} not divisible by the "
                  f"{ep}-device ep axis")
    if cand.topology in ("kvp", "kvp-tp"):
        kvp = mesh_axes.get("kvp", 1)
        guard(cand.kv_pool_blocks > 0,
              "KVP_DECODE shards the paged pool's kv-head plane; it "
              "requires KV_POOL_BLOCKS")
        fields = desc.get("kvp_divisors")
        if fields is None:
            # a family that never declared which config field the
            # kvp axis divides is unreviewable, not implicitly legal
            guard(False,
                  f"KVP_DECODE: {type(config).__name__}'s family "
                  "declares no kvp_divisors in its SHARDING_DESCRIPTOR "
                  "— the pool-plane sharding is unreviewable")
        else:
            for field in fields:
                v = getattr(config, field)
                guard(v % kvp == 0,
                      f"KVP_DECODE: {field}={v} not divisible by the "
                      f"{kvp}-device kvp axis (pool planes shard whole "
                      "kv heads)")
        if cand.topology == "kvp-tp":
            tp = mesh_axes.get("tp", 1)
            for field in desc.get("tp_divisors", ()):
                v = getattr(config, field)
                guard(v % tp == 0,
                      f"TP_DECODE: {field}={v} not divisible by the "
                      f"{tp}-device tp axis (attention shards whole "
                      "heads)")
    if findings:
        return findings, None

    # semantic verifier gates
    pspecs = None
    if cand.topology == "pp":
        findings.extend(semantic.check_stage_contracts(
            module, config, cand.boundaries, max_seq=min(max_seq, 32),
            where=where))
        findings.extend(semantic.check_ring_program(cand.n_stages, where))
    if cand.topology in ("tp", "ep", "kvp-tp"):
        pspecs = derive_pspecs(module, config, mesh_axes)
        findings.extend(semantic.check_pspec_tree(
            pspecs, param_avals(module, config), mesh_axes, where))
    if cand.topology in ("kvp", "kvp-tp"):
        # the pool-plane spec itself through the SAME pspec validity
        # checks every hand-written spec goes through (placement.
        # check_pspec — the relocated single source of truth): the
        # [L, NB+1, 2, Hkv, bs, hd] planes shard whole kv heads (dim 3)
        # over kvp and nothing else
        from jax.sharding import PartitionSpec as P
        from .placement import check_pspec
        heads = getattr(config, "n_kv_head", config.n_head)
        plane = (config.n_layer, cand.kv_pool_blocks + 1, 2, heads,
                 cand.kv_block_size, config.head_dim)
        findings.extend(check_pspec(
            P(None, None, None, "kvp"), plane, mesh_axes,
            f"{where}:pool-plane"))
    if cand.kv_pool_blocks:
        heads = getattr(config, "n_kv_head", config.n_head)
        findings.extend(semantic.check_paged_contracts(
            n_layer=config.n_layer, num_blocks=cand.kv_pool_blocks,
            n_kv_head=heads, block_size=cand.kv_block_size,
            head_dim=config.head_dim, max_seq=max_seq,
            batches=(1, cand.max_batch), where=where))
    return findings, pspecs


# -- scoring -----------------------------------------------------------------


@dataclasses.dataclass
class PlanRow:
    candidate: Candidate
    ok: bool
    findings: List[Finding] = dataclasses.field(default_factory=list)
    comm_bytes_per_token: int = 0
    param_bytes_per_device: int = 0
    kv_bytes_per_device: int = 0
    act_bytes: int = 0
    hbm_bytes_per_device: int = 0
    programs: Dict[str, int] = dataclasses.field(default_factory=dict)
    programs_exact: bool = False
    cost_per_token: float = float("inf")
    note: str = ""

    @property
    def program_total(self) -> int:
        return sum(self.programs.values())

    def sort_key(self):
        c = self.candidate
        simplicity = (c.kv_pool_blocks > 0, c.batch_mode != "admission",
                      c.max_batch, c.n_stages, c.topology)
        return (not self.ok, self.cost_per_token, self.program_total,
                self.hbm_bytes_per_device, simplicity)

    def to_dict(self) -> dict:
        return {
            "config": dataclasses.asdict(self.candidate),
            "label": self.candidate.label(),
            "ok": self.ok,
            "cost_per_token": (None if math.isinf(self.cost_per_token)
                               else round(self.cost_per_token, 1)),
            "comm_bytes_per_token": self.comm_bytes_per_token,
            "param_bytes_per_device": self.param_bytes_per_device,
            "kv_bytes_per_device": self.kv_bytes_per_device,
            "peak_activation_bytes": self.act_bytes,
            "hbm_bytes_per_device": self.hbm_bytes_per_device,
            "programs": dict(self.programs),
            "program_total": self.program_total,
            "programs_exact": self.programs_exact,
            "serving_env": self.candidate.serving_env(),
            "note": self.note,
            "findings": [f.to_dict() for f in self.findings],
        }


def traffic_calls(traffic: Sequence[TrafficRow], max_batch: int):
    """The traffic mix as the ``GenerateCall`` rows the admission
    batcher would form: full ``max_batch``-wide rounds plus the
    remainder round per shape class."""
    from . import recompile as R
    greedy = R.greedy_sampling()
    calls = []
    for row in traffic:
        left = row.count
        while left > 0:
            b = min(left, max_batch)
            left -= b
            calls.append(R.GenerateCall(
                prompt_lens=(row.prompt_len,) * b, max_new=row.max_new,
                sampling=greedy))
    return calls


def count_programs(cand: Candidate, max_seq: int,
                   traffic: Sequence[TrafficRow],
                   ) -> Tuple[Dict[str, int], bool]:
    """Compiled-program population per entry point, via the EXISTING
    certifier machinery. Exact (== observed jit cache size, the
    recompile.certify guarantee) for admission-mode engine candidates
    and the solo paged runner. Iter-mode and pp rows are static
    ESTIMATES marked inexact: iter enumerates live widths
    1..max_batch (rows join/retire dynamically, each live width is a
    compiled program — paged-iter additionally merges the pool's
    gather/scatter movers per width, though admission-merge/CoW
    programs mint on demand and are not statically enumerable), and pp
    is keyed by the decoder's own (batch, prompt_len)/(batch, steps,
    sampling) structure but not yet pinned against a live multi-device
    cache."""
    from . import recompile as R
    desc = R.EngineDesc(max_seq=max_seq)
    if cand.batch_mode == "iter":
        pools: Dict[str, set] = {}
        for w in range(1, cand.max_batch + 1):
            wide = [TrafficRow(r.prompt_len, r.max_new, w) for r in traffic]
            for call in traffic_calls(wide, w):
                if cand.kv_pool_blocks:
                    paged = R.PagedDesc(max_seq=max_seq,
                                        block_size=cand.kv_block_size)
                    keysets = R.paged_runner_keys(desc, paged, call)
                else:
                    keysets = R.engine_call_keys(desc, call)
                for name, ks in keysets.items():
                    pools.setdefault(name, set()).update(ks)
        return {n: len(ks) for n, ks in pools.items()}, False
    calls = traffic_calls(traffic, cand.max_batch)
    if cand.kv_pool_blocks:
        paged = R.PagedDesc(max_seq=max_seq, block_size=cand.kv_block_size)
        # kvp rows shard the same paged movers: the population is the
        # certified single-device one, but not yet pinned against a
        # live kvp-mesh jit cache — estimate, like pp
        return (R.certify_paged(desc, paged, calls),
                cand.topology == "single")
    if cand.topology == "pp":
        keys_p, keys_d = set(), set()
        for call in calls:
            b = len(call.prompt_lens)
            keys_p.add((b, max(call.prompt_lens)))
            keys_d.add((b, call.max_new, call.sampling))
        return {"_prefill": len(keys_p), "_decode": len(keys_d)}, False
    return R.certify(desc, calls), True


def score_candidate(module, config, cand: Candidate,
                    mesh_axes: Dict[str, int], max_seq: int,
                    traffic: Sequence[TrafficRow], pspecs,
                    hbm_gb: float = DEFAULT_HBM_GB,
                    ici_byte_weight: Optional[float] = None) -> PlanRow:
    """Price one verifier-clean candidate. See the module docstring for
    the cost terms; everything here is avals and traced jaxprs.
    ``ici_byte_weight`` overrides the a-priori ``ICI_BYTE_WEIGHT`` —
    pass :func:`calibrate`'s measured value to score with this host's
    observed ICI cost instead of the model's guess."""
    row = PlanRow(candidate=cand, ok=True)
    conc = concurrency(traffic)
    eff_batch = max(1, min(cand.max_batch, conc))
    avals = param_avals(module, config)

    # params per device (pure kvp leaves params replicated — only the
    # pool planes shard; kvp-tp layers the descriptor-derived tp
    # sharding on top)
    if cand.topology in ("tp", "ep", "kvp-tp") and pspecs is not None:
        row.param_bytes_per_device = per_device_param_bytes(
            avals, pspecs, mesh_axes)
    elif cand.topology == "pp":
        from llm_sharding_demo_tpu.parallel import partition as Pt
        import jax
        specs = Pt.make_stage_specs(config.n_layer, cand.boundaries)
        stage_avals = jax.eval_shape(
            lambda p: Pt.partition_params(p, specs), avals)
        row.param_bytes_per_device = max(tree_bytes(s) for s in stage_avals)
    else:
        row.param_bytes_per_device = tree_bytes(avals)

    # KV state per device (the rows the config keeps resident)
    if cand.kv_pool_blocks:
        pool = kv_pool_bytes(config, cand.kv_pool_blocks,
                             cand.kv_block_size)
        kv_row = kv_cache_bytes(config, 1, max_seq)
        if cand.topology in ("kvp", "kvp-tp"):
            # pool planes shard whole kv heads over kvp: resident HBM
            # AND the per-token read stream both divide exactly (the
            # divisor gate already proved Hkv % kvp == 0)
            kvp = mesh_axes.get("kvp", 1)
            pool //= kvp
            kv_row //= kvp
        row.kv_bytes_per_device = pool
    else:
        kv_all = kv_cache_bytes(config, eff_batch, max_seq)
        if cand.topology == "pp":
            # a stage holds only its own layers' cache slice
            per = max((b - a) for a, b in zip(
                (0,) + cand.boundaries, cand.boundaries + (config.n_layer,)))
            kv_all = kv_all * per // config.n_layer
        elif cand.topology == "tp":
            tp = mesh_axes.get("tp", 1)
            heads = getattr(config, "n_kv_head", config.n_head)
            if heads % tp == 0:
                kv_all //= tp
        row.kv_bytes_per_device = kv_all
        kv_row = kv_all // eff_batch

    # comm per decode token
    if cand.topology == "pp":
        row.comm_bytes_per_token = pp_decode_comm_bytes(
            cand.n_stages, batch=eff_batch, module=module, config=config)
    elif cand.topology == "tp":
        row.comm_bytes_per_token = tp_decode_comm_bytes(
            config, eff_batch, mesh_axes["tp"])
    elif cand.topology == "ep":
        row.comm_bytes_per_token = ep_decode_comm_bytes(
            config, eff_batch, mesh_axes["ep"])
    elif cand.topology == "kvp":
        row.comm_bytes_per_token = kvp_decode_comm_bytes(
            config, eff_batch, mesh_axes["kvp"])
    elif cand.topology == "kvp-tp":
        # the two axes' schedules compose additively: per block the tp
        # psums AND the kvp partial-softmax gather both cross the ICI
        row.comm_bytes_per_token = (
            kvp_decode_comm_bytes(config, eff_batch, mesh_axes["kvp"])
            + tp_decode_comm_bytes(config, eff_batch, mesh_axes["tp"]))

    row.act_bytes = peak_activation_bytes(module, config, eff_batch,
                                          min(max_seq, 128))
    row.hbm_bytes_per_device = (row.param_bytes_per_device
                                + row.kv_bytes_per_device + row.act_bytes)
    budget = int(hbm_gb * (1 << 30))
    if row.hbm_bytes_per_device > budget:
        row.ok = False
        row.note = (f"infeasible: {row.hbm_bytes_per_device} bytes/device "
                    f"exceeds the {hbm_gb} GiB HBM budget")
        return row

    row.programs, row.programs_exact = count_programs(cand, max_seq, traffic)

    paged_overhead = (2 * kv_row / PAGED_SEG_STEPS
                      if cand.kv_pool_blocks else 0.0)
    weight_term = row.param_bytes_per_device / eff_batch
    ici_w = (ICI_BYTE_WEIGHT if ici_byte_weight is None
             else float(ici_byte_weight))
    row.cost_per_token = (weight_term + kv_row + paged_overhead
                          + ici_w * row.comm_bytes_per_token)
    return row


# -- the planner -------------------------------------------------------------


class CalibrationError(ValueError):
    """A calibration row is PRESENT in the journal but unparsable —
    malformed fields, non-numeric ratios, inconsistent byte splits.
    Distinct from a *skipped* row (tunnel down, off-chip), which is an
    honest environment fact and calibrates nothing (``None``): a
    malformed measurement silently falling back to the a-priori weight
    is exactly how a broken journal writer would hide for rounds."""


def calibrate(journal) -> Optional[float]:
    """Measured ICI byte weight from a bench journal's
    ``ici_byte_weight_calibration`` row (the measurement half of the
    measure->model loop, ROADMAP item 5): the row journals the
    compiled executable's network bytes against the model's formula as
    ``measured_over_modeled``, and the weight the row was measured
    AGAINST as ``ici_byte_weight`` — the calibrated weight is their
    product, so a plan scored with it prices ICI traffic at what this
    host's XLA actually scheduled. Accepts a raw bench payload, a
    ``BENCH_rNN.json`` driver row (``parsed`` wrapper), or the config
    row itself; returns None when the journal carries no calibration
    row at all or a genuinely SKIPPED one (e.g. off-chip) — callers
    fall back to the a-priori ``ICI_BYTE_WEIGHT``. A row that is
    present but unparsable (malformed/partial fields) raises
    :class:`CalibrationError` instead: silently scoring with the
    a-priori weight would hide a broken journal writer forever."""
    doc = journal
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc.get("parsed")
    if not isinstance(doc, dict):
        return None
    row = None
    if doc.get("name") == "ici_byte_weight_calibration":
        row = doc
    else:
        for cfg in doc.get("configs") or ():
            if isinstance(cfg, dict) \
                    and cfg.get("name") == "ici_byte_weight_calibration":
                row = cfg
                break
    if row is None or row.get("skipped") or row.get("error"):
        return None
    ratio = row.get("measured_over_modeled")
    if not isinstance(ratio, (int, float)) or isinstance(ratio, bool) \
            or ratio <= 0:
        raise CalibrationError(
            "ici_byte_weight_calibration row is present but its "
            f"measured_over_modeled={ratio!r} is not a positive number "
            "— refusing to fall back silently on a malformed row "
            "(skipped rows calibrate nothing; malformed rows fail "
            "loudly)")
    base = row.get("ici_byte_weight")
    if base is None:
        base = ICI_BYTE_WEIGHT        # older rows omit the base weight
    elif not isinstance(base, (int, float)) or isinstance(base, bool) \
            or base <= 0:
        raise CalibrationError(
            "ici_byte_weight_calibration row is present but its "
            f"ici_byte_weight={base!r} is not a positive number — "
            "the row does not say what weight it was measured against")
    return float(base) * float(ratio)


def plan(module, config, mesh_axes: Dict[str, int], max_seq: int = 64,
         traffic: Optional[Sequence[TrafficRow]] = None,
         max_batch_cap: int = 8, kv_pool_blocks: int = 0,
         kv_block_size: int = 16, hbm_gb: float = DEFAULT_HBM_GB,
         include_unsharded: bool = True,
         ici_byte_weight: Optional[float] = None) -> dict:
    """The library API behind ``python -m tools.graftcheck plan``:
    enumerate -> gate -> score -> rank. Returns the JSON-able payload
    (schema: docs/ARCHITECTURE.md "Planning"); ``chosen`` is the
    top-ranked verifier-clean feasible row, or None when nothing
    survives. ``ici_byte_weight`` (see :func:`calibrate`) re-prices
    every candidate's ICI term with a measured weight."""
    traffic = tuple(traffic) if traffic else DEFAULT_TRAFFIC
    rows: List[PlanRow] = []
    for cand in enumerate_candidates(module, config, mesh_axes, max_seq,
                                     max_batch_cap, kv_pool_blocks,
                                     kv_block_size,
                                     include_unsharded=include_unsharded):
        findings, pspecs = gate_candidate(module, config, cand, mesh_axes,
                                          max_seq)
        if findings:
            rows.append(PlanRow(candidate=cand, ok=False, findings=findings,
                                note="rejected by the semantic verifier"))
            continue
        rows.append(score_candidate(module, config, cand, mesh_axes,
                                    max_seq, traffic, pspecs, hbm_gb,
                                    ici_byte_weight=ici_byte_weight))
    rows.sort(key=PlanRow.sort_key)
    chosen = next((r for r in rows if r.ok), None)
    return {
        "model": type(config).__name__,
        "mesh": dict(mesh_axes),
        "ici_byte_weight": (ICI_BYTE_WEIGHT if ici_byte_weight is None
                            else float(ici_byte_weight)),
        # provenance for the weight above: a payload scored with a
        # measured weight (startup calibration or a live grafttrend
        # refit) must be distinguishable from one priced a-priori —
        # two plan files can disagree on ranking for THIS reason alone
        "ici_byte_weight_source": ("a-priori" if ici_byte_weight is None
                                   else "provided"),
        "max_seq": max_seq,
        "traffic": [r.to_dict() for r in traffic],
        "plan": [r.to_dict() for r in rows],
        "chosen": chosen.to_dict() if chosen is not None else None,
        "rejected": sum(1 for r in rows if not r.ok),
    }


def plan_for_serving(config, n_devices: int, max_seq: int,
                     traffic: Optional[Sequence[TrafficRow]] = None,
                     max_batch_cap: int = 8, kv_pool_blocks: int = 0,
                     kv_block_size: int = 16,
                     hbm_gb: float = DEFAULT_HBM_GB) -> dict:
    """The AUTO_PLAN entry point: given the loaded model config and the
    pod's device count, search every single-axis mesh assignment of the
    devices (tp / ep / pp / unsharded) and return one merged payload
    whose ``chosen`` row is the global best."""
    from llm_sharding_demo_tpu.models import family_module
    module = family_module(config)
    meshes: List[Dict[str, int]] = [{}]
    if n_devices > 1:
        for axis in ("tp", "ep", "pp"):
            meshes.append({axis: n_devices})
    merged: Optional[dict] = None
    all_rows: List[dict] = []
    best: Optional[dict] = None
    for mesh_axes in meshes:
        # unsharded candidates score once (the no-mesh pass) — they are
        # mesh-independent, and re-scoring them per candidate mesh
        # would both waste startup tracing and duplicate table rows
        payload = plan(module, config, mesh_axes, max_seq=max_seq,
                       traffic=traffic, max_batch_cap=max_batch_cap,
                       kv_pool_blocks=kv_pool_blocks,
                       kv_block_size=kv_block_size, hbm_gb=hbm_gb,
                       include_unsharded=not mesh_axes)
        if merged is None:
            merged = payload
        for row in payload["plan"]:
            row = dict(row)
            row["mesh"] = dict(mesh_axes)
            all_rows.append(row)
        c = payload["chosen"]
        if c is not None:
            c = dict(c, mesh=dict(mesh_axes))
            if best is None or (c["cost_per_token"], c["program_total"]) < \
                    (best["cost_per_token"], best["program_total"]):
                best = c
    assert merged is not None
    all_rows.sort(key=lambda r: (not r["ok"],
                                 r["cost_per_token"] is None,
                                 r["cost_per_token"] or 0))
    merged["plan"] = all_rows
    merged["chosen"] = best
    merged["mesh"] = {"devices": n_devices}
    merged["rejected"] = sum(1 for r in all_rows if not r["ok"])
    return merged
