"""graftcheck fleet pass: fleet-topology static analysis (compile-free).

graftfleet (``llm_sharding_demo_tpu/fleet/`` + ``serving/router.py``)
disaggregates prefill from decode replicas and hands KV blocks across
that boundary through the pool's content-keyed prefix registry. Every
property that makes the handoff safe is easy to silently lose in a
refactor: a new code path could speak the replica wire outside the
router's breaker/deadline discipline, touch the registry surface
outside the lease-checked adoption scopes, invent a role the topology
never declared, or re-derive the affinity key until the router and the
registry disagree about what "same prefix" means. Mirroring the
graftsan/graftlock/graftfault static+dynamic split, this module is the
STATIC half of the fleet subsystem: topology becomes a DECLARED
contract, enforced by AST rules over the production tree (the dynamic
half — the router, the shared-pool harness, the seeded shed/affinity
replay — lives in ``fleet/`` and ``serving/router.py``).

In-file declarations (the registration-annotation idiom of
``FAULT_POLICY`` / ``GUARDED_STATE`` / ``POOL_MOVER_SCOPES``):

- ``FLEET_ROLES``: dict literal ``{role: description}`` — THE role
  vocabulary (``fleet/topology.py``).
- ``HANDOFF_POLICY``: dict literal ``{hop: (from_role, to_role,
  lifetime_doc)}`` — one entry per cross-replica hop; the third field
  documents what crosses the wire and who owns which pool refs when.
- ``HOP_SCOPES``: tuple of function qualnames allowed to speak the
  replica wire directly (``serving/router.py``) — every other dispatch
  must go through ``_hop(...)`` naming a declared HANDOFF_POLICY entry.
- ``HANDOFF_SCOPES``: tuple of function qualnames allowed to touch the
  allocator's content-keyed registry surface (``lookup_prefix`` /
  ``register_prefix``) — the prefill->decode adoption boundary
  (``runtime/prefix_cache.py``).
- ``AFFINITY_KEY_SOURCE``: ``"relpath:Qualified.name"`` string naming
  THE function the router's affinity key must come from
  (``fleet/affinity.py`` → the prefix registry's own ``_key``).

Rules (ids in brackets; suppressions ride the shared baseline):

- [fleet-role]             malformed FLEET_ROLES / HANDOFF_POLICY
                           declarations, a HANDOFF_POLICY endpoint
                           role missing from FLEET_ROLES, a role
                           string compared against a ``fleet_role`` /
                           ``.role`` attribute that the registry does
                           not know, or a registered role nothing in
                           the tree references (stale vocabulary).
- [undeclared-replica-hop] a replica wire call (``client.post/get``)
                           in fleet code outside a declared HOP_SCOPES
                           scope (or with no declaration at all), a
                           stale HOP_SCOPES entry, a ``_hop(...)``
                           dispatch whose hop name is not a string
                           literal or names no HANDOFF_POLICY entry,
                           or a declared hop no dispatch ever takes
                           (stale contract).
- [handoff-provenance]     the registry surface touched outside a
                           declared HANDOFF_SCOPES scope — the block-
                           lifetime argument for the adoption boundary
                           only holds inside the scopes graftsan's
                           lease discipline covers, so a module
                           declaring HANDOFF_SCOPES must also carry
                           the POOL_MOVER_SCOPES contract — plus stale
                           scope entries.
- [affinity-key-drift]     AFFINITY_KEY_SOURCE unparseable or naming a
                           function that does not exist, the declaring
                           module never calling the source, or a
                           content digest (hashlib / builtin ``hash``)
                           inside a source-calling function — the
                           router re-deriving "same prefix" is exactly
                           the drift that scatters warm prefixes
                           across replicas.

``--strict`` additionally fails a VACUOUS pass (a declaration-carrying
module none of whose contract entries match anything live — the fleet
contract stopped seeing the code); ``cli.run --json`` carries
``fleet_checks`` / ``fleet_policies`` / ``fleet_vacuous``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _dotted, _module_assign, _parents, _scope_of

FLEET_RULE_IDS = ("fleet-role", "undeclared-replica-hop",
                  "handoff-provenance", "affinity-key-drift")

# where replica wire calls are held to HOP_SCOPES (the fleet's own
# surface; loadgen/tests drive TestClients too, but only fleet code
# carries the cross-replica hop contract)
_FLEET_PREFIXES = ("llm_sharding_demo_tpu/fleet/",)
_FLEET_FILES = {"llm_sharding_demo_tpu/serving/router.py"}

# the registry's def site: its own body is the implementation, not a
# consumer of the handoff surface
_REGISTRY_DEF_RELPATH = "llm_sharding_demo_tpu/runtime/kv_pool.py"
_REGISTRY_SURFACE = {"lookup_prefix", "register_prefix"}

# attribute names whose string comparisons name fleet roles
_ROLE_ATTRS = {"fleet_role", "role"}


def _is_fleet_module(relpath: str) -> bool:
    return (relpath in _FLEET_FILES
            or any(relpath.startswith(p) for p in _FLEET_PREFIXES))


# -- declarations -------------------------------------------------------------


def _str_dict(node: ast.AST) -> Optional[List[Tuple[str, ast.AST]]]:
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        out.append((k.value, v))
    return out


def declared_roles(mod: L.ModuleInfo,
                   ) -> Tuple[Optional[Set[str]], int, List[str]]:
    """``FLEET_ROLES`` -> (roles, decl line, malformed messages)."""
    stmt = _module_assign(mod, "FLEET_ROLES")
    if stmt is None:
        return None, 0, []
    entries = _str_dict(stmt.value)
    if entries is None:
        return set(), stmt.lineno, [
            "FLEET_ROLES must be a dict literal with string role keys "
            "(the fleet pass reads the vocabulary statically)"]
    bad = [f"role {k!r}: description must be a string literal"
           for k, v in entries
           if not (isinstance(v, ast.Constant)
                   and isinstance(v.value, str))]
    return {k for k, _ in entries}, stmt.lineno, bad


def declared_handoffs(mod: L.ModuleInfo,
                      ) -> Tuple[Optional[Dict[str, Tuple[str, str, str]]],
                                 int, List[str]]:
    """``HANDOFF_POLICY`` -> ({hop: (from, to, doc)}, decl line,
    malformed messages)."""
    stmt = _module_assign(mod, "HANDOFF_POLICY")
    if stmt is None:
        return None, 0, []
    entries = _str_dict(stmt.value)
    if entries is None:
        return {}, stmt.lineno, [
            "HANDOFF_POLICY must be a dict literal with string hop keys"]
    out: Dict[str, Tuple[str, str, str]] = {}
    bad: List[str] = []
    for hop, v in entries:
        vals: Optional[List[str]] = None
        if isinstance(v, (ast.Tuple, ast.List)):
            vals = [e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            if len(vals) != len(v.elts):
                vals = None
        if vals is None or len(vals) != 3:
            bad.append(f"hop {hop!r}: policy must be a (from_role, "
                       "to_role, block_lifetime_doc) string triple")
            continue
        out[hop] = (vals[0], vals[1], vals[2])
    return out, stmt.lineno, bad


def _declared_scopes(mod: L.ModuleInfo, name: str,
                     ) -> Tuple[Optional[Set[str]], int]:
    stmt = _module_assign(mod, name)
    if stmt is None:
        return None, 0
    vals = L._string_tuple(stmt.value)
    return (vals if vals is not None else set()), stmt.lineno


def declared_affinity_source(mod: L.ModuleInfo,
                             ) -> Tuple[Optional[str], int]:
    stmt = _module_assign(mod, "AFFINITY_KEY_SOURCE")
    if stmt is None:
        return None, 0
    if (isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)):
        return stmt.value.value, stmt.lineno
    return "", stmt.lineno


# -- use extraction -----------------------------------------------------------


def _role_literals(mod: L.ModuleInfo) -> List[Tuple[int, str, str]]:
    """String literals compared against a role attribute:
    ``cfg.fleet_role != "prefill"`` / ``self.fleet_role not in ("",
    "prefill", "decode")`` / ``r.role == "router"`` ->
    [(line, scope-attr, literal)]. Only fleet-surface comparisons are
    meaningful role uses; everything else compares other vocabulary."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        attr = None
        for s in sides:
            d = _dotted(s)
            if d is not None and d.rpartition(".")[2] in _ROLE_ATTRS:
                attr = d.rpartition(".")[2]
        if attr is None:
            continue
        for s in sides:
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                out.append((s.lineno, attr, s.value))
            elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                for e in s.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        out.append((e.lineno, attr, e.value))
    return out


def _wire_calls(mod: L.ModuleInfo) -> List[Tuple[int, str, str]]:
    """Replica wire touchpoints: ``<...>client.post/get(...)`` ->
    [(line, scope, dotted receiver)]."""
    parents = _parents(mod.tree)
    out = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("post", "get")):
            continue
        recv = _dotted(node.func.value)
        if recv is None or recv.rpartition(".")[2] != "client":
            continue
        out.append((node.lineno, _scope_of(node, parents, mod), recv))
    return out


def _hop_dispatches(mod: L.ModuleInfo,
                    ) -> List[Tuple[int, str, Optional[str]]]:
    """``*._hop("name", ...)`` dispatch sites -> [(line, scope,
    literal hop name or None when not a string literal)]."""
    parents = _parents(mod.tree)
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        named = ((isinstance(f, ast.Attribute) and f.attr == "_hop")
                 or (isinstance(f, ast.Name) and f.id == "_hop"))
        if not named:
            continue
        hop = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            hop = node.args[0].value
        out.append((node.lineno, _scope_of(node, parents, mod), hop))
    return out


def _registry_calls(mod: L.ModuleInfo) -> List[Tuple[int, str, str]]:
    """Content-keyed registry surface calls (``.lookup_prefix`` /
    ``.register_prefix``) -> [(line, scope, method)]."""
    parents = _parents(mod.tree)
    out = []
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTRY_SURFACE):
            out.append((node.lineno, _scope_of(node, parents, mod),
                        node.func.attr))
    return out


def _digest_calls_in(fn: ast.AST) -> List[int]:
    """Content-digest call lines inside ``fn``'s own body (hashlib.* or
    builtin ``hash``) — an independent key derivation."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "hash":
            out.append(node.lineno)
        d = _dotted(f) or ""
        if d.split(".", 1)[0] == "hashlib":
            out.append(node.lineno)
    return out


# -- the pass -----------------------------------------------------------------


def run_fleet(root: str, paths: Optional[List[str]] = None,
              ) -> Tuple[List[Finding], dict]:
    """The whole static pass over the production surface ->
    (findings, summary). ``summary`` carries ``fleet_checks`` (real
    analysis units: declarations validated, hop dispatches resolved,
    wire/registry sites scoped, role literals checked, affinity
    sources resolved — the vacuity guard on the pass itself),
    ``fleet_policies`` (per-declaring-module count of contract entries
    matching something live) and ``vacuous`` (declaration-carrying
    modules whose contract matches nothing — the strict driver fails
    these)."""
    mods: List[L.ModuleInfo] = []
    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is not None:
            mods.append(mod)

    findings: List[Finding] = []
    checks = 0
    policies: Dict[str, int] = {}
    vacuous: List[str] = []

    # -- phase 1: collect declarations ------------------------------------
    roles: Set[str] = set()
    roles_mod: Optional[L.ModuleInfo] = None
    roles_line = 0
    handoffs: Dict[str, Tuple[str, str, str]] = {}
    handoffs_mod: Optional[L.ModuleInfo] = None
    handoffs_line = 0
    hop_scopes: Dict[str, Tuple[Set[str], int]] = {}       # relpath ->
    handoff_scopes: Dict[str, Tuple[Set[str], int]] = {}
    affinity: Dict[str, Tuple[str, int]] = {}

    for mod in mods:
        r, line, bad = declared_roles(mod)
        if r is not None:
            checks += 1
            roles |= r
            roles_mod, roles_line = mod, line
            for msg in bad:
                findings.append(Finding("fleet-role", mod.relpath, line,
                                        "<module>", msg))
        h, hline, bad = declared_handoffs(mod)
        if h is not None:
            checks += 1
            handoffs.update(h)
            handoffs_mod, handoffs_line = mod, hline
            for msg in bad:
                findings.append(Finding("fleet-role", mod.relpath, hline,
                                        "<module>", msg))
        s, sline = _declared_scopes(mod, "HOP_SCOPES")
        if s is not None:
            hop_scopes[mod.relpath] = (s, sline)
        s, sline = _declared_scopes(mod, "HANDOFF_SCOPES")
        if s is not None:
            handoff_scopes[mod.relpath] = (s, sline)
        src, aline = declared_affinity_source(mod)
        if src is not None:
            affinity[mod.relpath] = (src, aline)

    # -- fleet-role: endpoint completeness --------------------------------
    role_uses: Set[str] = set()
    if handoffs_mod is not None:
        for hop, (src_role, dst_role, _doc) in sorted(handoffs.items()):
            checks += 1
            for endpoint in (src_role, dst_role):
                if roles and endpoint not in roles:
                    findings.append(Finding(
                        "fleet-role", handoffs_mod.relpath,
                        handoffs_line, "<module>",
                        f"HANDOFF_POLICY hop {hop!r} names endpoint "
                        f"role {endpoint!r}, which FLEET_ROLES does "
                        "not register — declare the role or fix the "
                        "hop"))
                else:
                    role_uses.add(endpoint)

    # -- per-module use scans ---------------------------------------------
    dispatched: Set[str] = set()
    wire_scoped: Dict[str, Set[str]] = {}      # relpath -> live scopes
    registry_scoped: Dict[str, Set[str]] = {}

    for mod in mods:
        # role literals (fleet surface + any module declaring roles,
        # i.e. wherever the vocabulary is actually spoken)
        if roles and (_is_fleet_module(mod.relpath)
                      or mod.relpath.startswith(
                          "llm_sharding_demo_tpu/serving/")
                      or mod.relpath.endswith("utils/config.py")):
            for line, attr, lit in _role_literals(mod):
                checks += 1
                if lit == "":
                    continue          # "" = standalone, not a role
                if lit not in roles:
                    findings.append(Finding(
                        "fleet-role", mod.relpath, line, attr,
                        f"role literal {lit!r} compared against "
                        f"{attr!r} is not registered in FLEET_ROLES "
                        f"({sorted(roles)}) — an unregistered role "
                        "can neither be routed to nor checked"))
                else:
                    role_uses.add(lit)

        # hop dispatches
        for line, scope, hop in _hop_dispatches(mod):
            checks += 1
            if hop is None:
                findings.append(Finding(
                    "undeclared-replica-hop", mod.relpath, line, scope,
                    "_hop dispatch whose hop name is not a string "
                    "literal — the fleet pass cannot match it against "
                    "HANDOFF_POLICY (name the declared hop inline)"))
            elif hop not in handoffs:
                findings.append(Finding(
                    "undeclared-replica-hop", mod.relpath, line, scope,
                    f"_hop dispatch names {hop!r} but HANDOFF_POLICY "
                    "declares no such hop — what crosses this wire "
                    "and who owns the blocks afterward?"))
            else:
                dispatched.add(hop)

        # wire calls in fleet code
        if _is_fleet_module(mod.relpath):
            calls = _wire_calls(mod)
            declared, decl_line = hop_scopes.get(mod.relpath,
                                                 (None, 0))
            for line, scope, recv in calls:
                checks += 1
                if declared is None:
                    findings.append(Finding(
                        "undeclared-replica-hop", mod.relpath, line,
                        scope,
                        f"fleet module speaks the replica wire "
                        f"({recv}.post/get) but declares no "
                        "HOP_SCOPES — the breaker/deadline/shed "
                        "discipline only covers dispatch through "
                        "declared scopes"))
                elif scope not in declared:
                    findings.append(Finding(
                        "undeclared-replica-hop", mod.relpath, line,
                        scope,
                        f"replica wire call in {scope!r}, which "
                        "HOP_SCOPES does not declare — route the "
                        "dispatch through _hop so the per-target "
                        "breaker and deadline budget cover it"))
                else:
                    wire_scoped.setdefault(mod.relpath,
                                           set()).add(scope)
            if declared is not None:
                for scope in sorted(
                        declared - wire_scoped.get(mod.relpath, set())):
                    checks += 1
                    findings.append(Finding(
                        "undeclared-replica-hop", mod.relpath,
                        decl_line, scope,
                        f"HOP_SCOPES declares {scope!r} but it makes "
                        "no replica wire call (stale declaration)"))

        # registry surface provenance
        if mod.relpath != _REGISTRY_DEF_RELPATH:
            calls = _registry_calls(mod)
            declared, decl_line = handoff_scopes.get(mod.relpath,
                                                     (None, 0))
            for line, scope, meth in calls:
                checks += 1
                if declared is None:
                    findings.append(Finding(
                        "handoff-provenance", mod.relpath, line, scope,
                        f"{meth} call on the content-keyed registry "
                        "outside any HANDOFF_SCOPES declaration — the "
                        "prefill->decode adoption boundary must be "
                        "enumerated so block lifetime is reviewable"))
                elif scope not in declared:
                    findings.append(Finding(
                        "handoff-provenance", mod.relpath, line, scope,
                        f"{meth} call in {scope!r}, which "
                        "HANDOFF_SCOPES does not declare — registry "
                        "handoff outside the declared adoption "
                        "boundary"))
                else:
                    registry_scoped.setdefault(mod.relpath,
                                               set()).add(scope)
            if declared is not None:
                for scope in sorted(
                        declared
                        - registry_scoped.get(mod.relpath, set())):
                    checks += 1
                    findings.append(Finding(
                        "handoff-provenance", mod.relpath, decl_line,
                        scope,
                        f"HANDOFF_SCOPES declares {scope!r} but it "
                        "touches no registry surface (stale "
                        "declaration)"))
                # the lifetime argument rides graftsan's lease
                # discipline: the module enumerating the adoption
                # boundary must carry the POOL_MOVER_SCOPES contract
                checks += 1
                if _module_assign(mod, "POOL_MOVER_SCOPES") is None:
                    findings.append(Finding(
                        "handoff-provenance", mod.relpath, decl_line,
                        "<module>",
                        "module declares HANDOFF_SCOPES but no "
                        "POOL_MOVER_SCOPES — the adoption boundary's "
                        "block-lifetime claim depends on graftsan's "
                        "lease-checked mover scopes"))

    # -- stale hop contracts ----------------------------------------------
    if handoffs_mod is not None:
        for hop in sorted(set(handoffs) - dispatched):
            checks += 1
            findings.append(Finding(
                "undeclared-replica-hop", handoffs_mod.relpath,
                handoffs_line, "<module>",
                f"HANDOFF_POLICY declares hop {hop!r} but no _hop "
                "dispatch takes it (stale contract)"))

    # -- stale roles -------------------------------------------------------
    if roles_mod is not None:
        for role in sorted(roles - role_uses):
            checks += 1
            findings.append(Finding(
                "fleet-role", roles_mod.relpath, roles_line,
                "<module>",
                f"FLEET_ROLES registers {role!r} but no handoff "
                "endpoint or role check references it (stale "
                "vocabulary)"))

    # -- affinity-key drift ------------------------------------------------
    by_relpath = {m.relpath: m for m in mods}
    for relpath, (src, line) in sorted(affinity.items()):
        mod = by_relpath[relpath]
        checks += 1
        target_rel, sep, qual = src.partition(":")
        target = by_relpath.get(target_rel) if sep else None
        if target is None and sep:
            # source file may sit outside the scanned paths subset
            # (rule fixtures); try indexing it directly
            cand = os.path.join(root, target_rel)
            if os.path.exists(cand):
                target = L.index_module(cand, root)
        if not sep or not qual or target is None:
            findings.append(Finding(
                "affinity-key-drift", relpath, line, "<module>",
                f"AFFINITY_KEY_SOURCE {src!r} must be "
                "'relpath:Qualified.name' naming an existing module "
                "— the router's key must trace to the registry's own "
                "derivation"))
            continue
        if qual not in target.functions:
            findings.append(Finding(
                "affinity-key-drift", relpath, line, "<module>",
                f"AFFINITY_KEY_SOURCE names {qual!r}, which "
                f"{target_rel} does not define — the declared key "
                "source is gone (drift, or a stale declaration)"))
            continue
        leaf = qual.rpartition(".")[2]
        callers = []
        for fn_qual, fn in sorted(mod.functions.items()):
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == leaf):
                    callers.append((fn_qual, fn))
                    break
        checks += len(callers)
        if not callers:
            findings.append(Finding(
                "affinity-key-drift", relpath, line, "<module>",
                f"module declares AFFINITY_KEY_SOURCE but never calls "
                f"{qual!r} — the affinity key is not derived from the "
                "registry's own content keys"))
        for fn_qual, fn in callers:
            for dline in _digest_calls_in(fn):
                findings.append(Finding(
                    "affinity-key-drift", relpath, dline, fn_qual,
                    f"{fn_qual} derives the affinity key via "
                    f"{qual!r} but ALSO digests content itself "
                    "(hashlib/hash) — two derivations of 'same "
                    "prefix' is exactly the drift that scatters warm "
                    "prefixes across replicas"))
        policies[relpath] = (policies.get(relpath, 0)
                             + (1 if callers else 0))
        if not callers:
            vacuous.append(relpath)

    # -- vacuity accounting ------------------------------------------------
    if roles_mod is not None:
        live = len(roles & role_uses)
        policies[roles_mod.relpath] = policies.get(roles_mod.relpath, 0)
        if roles and not live:
            vacuous.append(roles_mod.relpath)
    if handoffs_mod is not None:
        live = len(set(handoffs) & dispatched)
        policies[handoffs_mod.relpath] = (
            policies.get(handoffs_mod.relpath, 0) + live)
        if handoffs and not live:
            vacuous.append(handoffs_mod.relpath)
    for relpath, (declared, _line) in sorted(hop_scopes.items()):
        live = len(declared & wire_scoped.get(relpath, set()))
        policies[relpath] = policies.get(relpath, 0) + live
        if declared and not live:
            vacuous.append(relpath)
    for relpath, (declared, _line) in sorted(handoff_scopes.items()):
        live = len(declared & registry_scoped.get(relpath, set()))
        policies[relpath] = policies.get(relpath, 0) + live
        if declared and not live:
            vacuous.append(relpath)

    summary = {
        "fleet_checks": checks,
        "fleet_policies": policies,
        "vacuous": sorted(set(vacuous)),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
