"""Pass 1: semantic contract verification by abstract evaluation.

Everything here runs under ``jax.eval_shape`` / ``jax.make_jaxpr`` on
CPU-mesh stand-ins (``jax.sharding.AbstractMesh``): shapes and dtypes
propagate through the REAL model/partition code, but no model program is
compiled and no device computes — the whole pass traces in well under a
second, so it runs on every test invocation.

Checks (each a function usable standalone on fixtures; ``run_semantic``
drives them over the registry):

- **Inter-stage contracts** (``check_stage_contracts``): for a family x
  partition plan, every stage's output aval must equal the next stage's
  input aval — ``[B, S]`` int32 into stage 0, the family hidden aval
  ``[B, S, D]`` (engine dtype) between stages (uneven/padded plans
  included), ``[B, S, vocab]`` out of the last — and each stage's cache
  must come back shape/dtype-identical (the decode scan carries it).
- **Partition plan validity** (``check_partition_plan``): overlapping /
  non-exhaustive / empty-stage plans are rejected with the partitioner's
  own diagnostic, surfaced as a finding.
- **Padded stacking round-trip** (``check_padded_stacking``): for uneven
  plans, ``unstack(stack(params))`` must reproduce the block avals
  exactly and the validity mask must count exactly ``n_layer`` true
  rows.
- **PartitionSpec validity** (``check_pspec_tree``): every spec leaf
  names only axes the mesh has, has rank <= array rank, uses no mesh
  axis twice, and shards only dims divisible by the axis size.
- **ppermute bijection** (``check_permutation`` /
  ``collect_ppermutes``): the stage-ring permutation must be a partial
  bijection over the axis (each source/destination at most once, all in
  range). ``collect_ppermutes`` extracts the pairs from a traced
  function's jaxpr (recursing into scan/while/cond/pjit/shard_map
  bodies), so the property is checked on what the program WILL run, not
  on what a docstring says.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

_PARTITION_PATH = "llm_sharding_demo_tpu/parallel/partition.py"
_PPDECODE_PATH = "llm_sharding_demo_tpu/parallel/ppdecode.py"
_SPMD_PATH = "llm_sharding_demo_tpu/parallel/spmd.py"


# -- partition plans ---------------------------------------------------------


def check_partition_plan(n_layer: int, boundaries: Sequence[int],
                         where: str = "plan") -> List[Finding]:
    """A plan must partition [0, n_layer) disjointly and exhaustively;
    the partitioner's ValueError is the precise diagnostic."""
    from llm_sharding_demo_tpu.parallel import partition as Pt
    try:
        Pt.make_stage_specs(n_layer, boundaries)
    except ValueError as e:
        return [Finding("stage-contract", _PARTITION_PATH, 1, where,
                        f"rejected partition plan: {e}")]
    return []


def check_spec_list(specs, n_layer: int, where: str = "specs",
                    ) -> List[Finding]:
    """``validate_specs`` as a finding source — overlapping stages,
    gaps, and index/n_stages inconsistencies in an externally built
    stage list."""
    from llm_sharding_demo_tpu.parallel import partition as Pt
    try:
        Pt.validate_specs(specs, n_layer)
    except ValueError as e:
        return [Finding("stage-contract", _PARTITION_PATH, 1, where,
                        f"rejected stage list: {e}")]
    return []


# -- inter-stage shape/dtype contracts ---------------------------------------


def _param_avals(module, config):
    import jax
    key = jax.random.PRNGKey(0)
    return jax.eval_shape(lambda k: module.init_params(config, k), key)


def check_stage_chain(stage_fns, first_in_aval, mid_aval, last_out_aval,
                      where: str) -> List[Finding]:
    """Generic chain checker: ``stage_fns[i]`` maps (x_aval) ->
    (out_aval, cache_delta_ok: bool). Used by the fixture tests with
    deliberately broken stages; ``check_stage_contracts`` builds the
    real stage closures and delegates here."""
    import jax
    findings: List[Finding] = []
    x = first_in_aval
    n = len(stage_fns)
    for i, fn in enumerate(stage_fns):
        try:
            out, cache_ok = fn(x)
        except Exception as e:  # noqa: BLE001 — a trace abort IS the finding
            findings.append(Finding(
                "stage-contract", _PARTITION_PATH, 1, where,
                f"stage {i} rejects its input aval "
                f"{tuple(x.shape)}/{x.dtype}: {type(e).__name__}: {e}"))
            return findings
        expect = last_out_aval if i == n - 1 else mid_aval
        if (tuple(out.shape) != tuple(expect.shape)
                or out.dtype != expect.dtype):
            findings.append(Finding(
                "stage-contract", _PARTITION_PATH, 1, where,
                f"stage {i} emits {tuple(out.shape)}/{out.dtype}, the "
                f"{'head contract' if i == n - 1 else 'next stage'} "
                f"expects {tuple(expect.shape)}/{expect.dtype}"))
        if not cache_ok:
            findings.append(Finding(
                "stage-contract", _PARTITION_PATH, 1, where,
                f"stage {i} returns a cache whose avals differ from its "
                "input cache (the decode scan carries it fixed-shape)"))
        x = out
    return findings


def check_stage_contracts(module, config, boundaries: Sequence[int],
                          batch: int = 2, seq: int = 6, max_seq: int = 32,
                          where: str = "", dtype=None) -> List[Finding]:
    """The registry-driven form: build the plan's stage closures over
    ``partition.stage_apply`` + per-stage caches and run the chain
    checker — all under eval_shape."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.parallel import partition as Pt
    dtype = dtype or jnp.float32
    bad = check_partition_plan(config.n_layer, boundaries, where)
    if bad:
        return bad
    specs = Pt.make_stage_specs(config.n_layer, boundaries)
    params_aval = _param_avals(module, config)
    stage_avals = jax.eval_shape(
        lambda p: Pt.partition_params(p, specs), params_aval)

    def tree_avals_equal(a, b) -> bool:
        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        return (len(la) == len(lb)
                and all(tuple(x.shape) == tuple(y.shape)
                        and x.dtype == y.dtype for x, y in zip(la, lb)))

    def make_fn(sp_aval, spec):
        cache_aval = jax.eval_shape(
            functools.partial(Pt.make_stage_cache, spec, config, batch,
                              max_seq, dtype))

        def fn(x_aval):
            out, cache_out = jax.eval_shape(
                lambda sp, x, c: Pt.stage_apply(sp, spec, config, x, c),
                sp_aval, x_aval, cache_aval)
            return out, tree_avals_equal(cache_aval, cache_out)

        return fn

    first_in = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    mid = jax.ShapeDtypeStruct((batch, seq, config.n_embd), dtype)
    last_out = jax.ShapeDtypeStruct((batch, seq, config.vocab_size),
                                    jnp.float32)
    fns = [make_fn(sp, spec) for sp, spec in zip(stage_avals, specs)]
    return check_stage_chain(fns, first_in, mid, last_out, where)


def check_padded_stacking(module, config, boundaries: Sequence[int],
                          where: str = "") -> List[Finding]:
    """Uneven-plan stacking: round-trip aval identity + mask row counts."""
    import jax
    import numpy as np

    from llm_sharding_demo_tpu.parallel import partition as Pt
    specs = Pt.make_stage_specs(config.n_layer, boundaries)
    params_aval = _param_avals(module, config)
    findings: List[Finding] = []

    rt = jax.eval_shape(
        lambda p: Pt.unstack_stage_params_padded(
            Pt.stack_stage_params_padded(p, specs)[0], specs), params_aval)
    orig = params_aval["blocks"]
    ra = jax.tree_util.tree_leaves(rt)
    oa = jax.tree_util.tree_leaves(orig)
    if (len(ra) != len(oa)
            or any(tuple(x.shape) != tuple(y.shape) or x.dtype != y.dtype
                   for x, y in zip(ra, oa))):
        findings.append(Finding(
            "stage-contract", _PARTITION_PATH, 1, where,
            "padded stack/unstack round-trip does not reproduce the "
            "block avals"))
    mask = np.asarray(Pt.stage_valid_mask(specs))
    per_max = max(s.n_blocks for s in specs)
    if mask.shape != (len(specs), per_max):
        findings.append(Finding(
            "stage-contract", _PARTITION_PATH, 1, where,
            f"validity mask shape {mask.shape}, want "
            f"{(len(specs), per_max)}"))
    elif int(mask.sum()) != config.n_layer:
        findings.append(Finding(
            "stage-contract", _PARTITION_PATH, 1, where,
            f"validity mask marks {int(mask.sum())} real layers, model "
            f"has {config.n_layer} — padded stages would execute the "
            "wrong layer set"))
    return findings


# -- PartitionSpec validity --------------------------------------------------


def check_pspec(spec, shape: Tuple[int, ...], mesh_axes: Dict[str, int],
                where: str) -> List[Finding]:
    """One spec against one array shape and a mesh's {axis: size}.

    Thin call-through: the axis-exists / rank-fits / axis-used-once /
    divisibility logic lives in the placement pass now (tools/
    graftcheck/placement.py — the single source of truth the planner's
    kvp gate also uses); the signature and the Finding shape (rule
    ``pspec`` against parallel/spmd.py) stay pinned here for the
    existing fixtures."""
    from .placement import check_pspec as _impl
    return _impl(spec, shape, mesh_axes, where)


def check_pspec_tree(specs_tree, aval_tree, mesh_axes: Dict[str, int],
                     where: str) -> List[Finding]:
    """Walk a pspec pytree against a matching aval pytree (dict-shaped,
    PartitionSpec leaves — the ``spmd.*_pspecs`` layout)."""
    import jax
    from jax.sharding import PartitionSpec
    findings: List[Finding] = []

    def walk(spec_node, aval_node, path: str):
        if isinstance(spec_node, PartitionSpec):
            leaves = jax.tree_util.tree_leaves(aval_node)
            if len(leaves) != 1:
                findings.append(Finding(
                    "pspec", _SPMD_PATH, 1, where,
                    f"{path}: one spec for {len(leaves)} arrays"))
                return
            findings.extend(check_pspec(
                spec_node, tuple(leaves[0].shape), mesh_axes,
                f"{where}/{path}"))
        elif isinstance(spec_node, dict):
            if not isinstance(aval_node, dict) or (
                    set(spec_node) != set(aval_node)):
                findings.append(Finding(
                    "pspec", _SPMD_PATH, 1, where,
                    f"{path}: spec tree keys {sorted(spec_node)} != "
                    f"param keys "
                    f"{sorted(aval_node) if isinstance(aval_node, dict) else type(aval_node).__name__}"))
                return
            for k in spec_node:
                walk(spec_node[k], aval_node[k], f"{path}.{k}" if path
                     else str(k))
        else:
            findings.append(Finding(
                "pspec", _SPMD_PATH, 1, where,
                f"{path}: unexpected spec node {type(spec_node).__name__}"))

    walk(specs_tree, aval_tree, "")
    return findings


# -- ppermute bijection ------------------------------------------------------


def check_permutation(pairs: Sequence[Tuple[int, int]], axis_size: int,
                      where: str) -> List[Finding]:
    """Partial-bijection check over a ``ppermute`` pair list: every
    source and every destination at most once, all indices in range.
    (A duplicate destination silently SUMS contributions on some
    backends and is undefined on others; a duplicate source double-sends
    — both are wiring bugs no runtime test at the wrong axis size would
    see.)"""
    problems: List[str] = []
    srcs: Dict[int, int] = {}
    dsts: Dict[int, int] = {}
    for i, (s, d) in enumerate(pairs):
        if not (0 <= s < axis_size) or not (0 <= d < axis_size):
            problems.append(
                f"pair {i} = ({s}, {d}) out of range for axis size "
                f"{axis_size}")
        if s in srcs:
            problems.append(
                f"source {s} appears in pairs {srcs[s]} and {i} — not a "
                "bijection (double-send)")
        srcs.setdefault(s, i)
        if d in dsts:
            problems.append(
                f"destination {d} appears in pairs {dsts[d]} and {i} — "
                "not a bijection (colliding receives)")
        dsts.setdefault(d, i)
    return [Finding("ppermute", _PPDECODE_PATH, 1, where, p)
            for p in problems]


def collect_ppermutes(fn, *avals) -> List[Tuple[tuple, tuple]]:
    """Trace ``fn`` (no compile, no execute) and return every
    ``ppermute`` equation's ``(axis_name, perm)`` — recursing into
    scan/while/cond/pjit/shard_map sub-jaxprs, so permutations inside
    compiled-loop bodies are found too."""
    import jax
    jaxpr = jax.make_jaxpr(fn)(*avals)
    found: List[Tuple[tuple, tuple]] = []

    def walk(jxp):
        for eqn in jxp.eqns:
            if eqn.primitive.name == "ppermute":
                found.append((tuple(eqn.params.get("axis_name", ())),
                              tuple(eqn.params.get("perm", ()))))
            for v in eqn.params.values():
                sub = getattr(v, "jaxpr", None)
                if sub is not None and hasattr(sub, "eqns"):
                    walk(sub)
                elif hasattr(v, "eqns"):
                    walk(v)
                elif isinstance(v, (tuple, list)):
                    for item in v:
                        sub = getattr(item, "jaxpr", None)
                        if sub is not None and hasattr(sub, "eqns"):
                            walk(sub)
                        elif hasattr(item, "eqns"):
                            walk(item)

    walk(jaxpr.jaxpr)
    return found


def check_ring_program(n_stages: int, where: str) -> List[Finding]:
    """Trace a shard_map stand-in that ppermutes with the REAL
    ``stage_ring_permutation`` over an AbstractMesh of ``n_stages``
    devices, extract the permutation from the jaxpr, and verify the
    bijection property — end-to-end through the same machinery a full
    program check would use, with zero devices."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from llm_sharding_demo_tpu.parallel.ppdecode import \
        stage_ring_permutation
    if n_stages < 2:
        # the declared helper must still behave (empty pair list)
        return check_permutation(stage_ring_permutation(n_stages),
                                 max(n_stages, 1), where)
    try:
        from jax import shard_map  # newer spelling
        smap = functools.partial(shard_map, axis_names={"pp"})
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap
    mesh = AbstractMesh((("pp", n_stages),))

    def per_device(x):
        return jax.lax.ppermute(x, "pp", stage_ring_permutation(n_stages))

    fn = smap(per_device, mesh=mesh, in_specs=(P("pp"),), out_specs=P("pp"))
    aval = jax.ShapeDtypeStruct((n_stages, 4), jnp.float32)
    perms = collect_ppermutes(fn, aval)
    if not perms:
        return [Finding("ppermute", _PPDECODE_PATH, 1, where,
                        "traced ring program contains no ppermute — "
                        "extraction or wiring broke")]
    findings: List[Finding] = []
    for axis_name, perm in perms:
        findings.extend(check_permutation(perm, n_stages, where))
    return findings


# -- overlap lint (collectives vs compute) -----------------------------------

# comm primitives the overlap rule (and the cost model's byte walker,
# tools/graftcheck/costmodel.py) recognize in a traced jaxpr
COMM_PRIMITIVES = ("ppermute", "psum", "all_gather", "all_to_all",
                   "reduce_scatter", "pmax", "pmin")

# primitives that are pure data movement/bookkeeping — never the compute
# a transfer could overlap with
_TRIVIAL_PRIMITIVES = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims", "transpose",
    "convert_element_type", "slice", "concatenate", "iota", "select_n",
    "pad", "rev", "copy", "stop_gradient", "eq", "ne", "lt", "le", "gt",
    "ge", "add", "sub", "and", "or", "not", "pvary", "pcast",
    "axis_index", "squeeze_p",
})


def _sub_jaxprs(eqn):
    """Every sub-jaxpr a primitive's params carry (scan/while/cond/pjit/
    shard_map bodies), normalized to plain Jaxpr objects."""
    subs = []
    for v in eqn.params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                subs.append(inner)
            elif hasattr(item, "eqns"):
                subs.append(item)
    return subs


def check_overlap_jaxpr(jaxpr, where: str, path: str,
                        scope: str) -> List[Finding]:
    """Walk a traced jaxpr; inside every ``scan`` body, flag each
    collective that (a) feeds the scan's carry outputs and (b) consumes
    in-body compute — i.e. the transfer for step k sits strictly between
    step k's compute and step k+1's compute with nothing scheduled to
    hide it. That is the serial-handoff shape TokenWeave-style
    double-buffering (split the per-stage batch, overlap microbatch k's
    collective with k+1's compute) removes; a baselined finding is the
    declared decision NOT to overlap yet."""
    findings: List[Finding] = []

    def analyze_scan_body(body, num_carry: int, where_in: str):
        from jax.core import Literal
        eqns = list(body.eqns)
        producer = {}
        for i, eqn in enumerate(eqns):
            for ov in eqn.outvars:
                producer[ov] = i
        # backward dependency closure per eqn (eqn indices it reads from)
        back: List[set] = []
        for i, eqn in enumerate(eqns):
            deps = set()
            for iv in eqn.invars:
                if isinstance(iv, Literal):
                    continue
                j = producer.get(iv)
                if j is not None:
                    deps.add(j)
                    deps |= back[j]
            back.append(deps)
        carry_outs = set(body.outvars[:num_carry])
        for i, eqn in enumerate(eqns):
            if eqn.primitive.name not in COMM_PRIMITIVES:
                continue
            # forward reach from this collective to the carry outputs
            reached = set(eqn.outvars)
            feeds_carry = bool(reached & carry_outs)
            for j in range(i + 1, len(eqns)):
                if i in back[j] or any(v in reached for v in eqns[j].invars):
                    back[j].add(i)
                    reached |= set(eqns[j].outvars)
            feeds_carry = feeds_carry or bool(reached & carry_outs)
            fed_by_compute = any(
                eqns[j].primitive.name not in _TRIVIAL_PRIMITIVES
                for j in back[i])
            if feeds_carry and fed_by_compute:
                findings.append(Finding(
                    "overlap", path, 1, scope,
                    f"{eqn.primitive.name} in {where_in} rides the scan "
                    "carry and consumes in-body compute: the transfer for "
                    "step k is strictly ordered between step k's and step "
                    "k+1's compute with no independent work to hide it "
                    "(double-buffer the microbatch to overlap, "
                    "TokenWeave-style)"))

    def walk(jxp):
        for eqn in jxp.eqns:
            if eqn.primitive.name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                analyze_scan_body(body, eqn.params["num_carry"],
                                  f"{where}: scan@{eqn.params.get('length')}")
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return findings


def build_ppdecode_programs(n_stages: int, batch: int = 1, seq: int = 8,
                            max_seq: int = 32, family: str = "gpt2",
                            module=None, config=None,
                            mesh=None) -> List[tuple]:
    """Trace the REAL ``PipelinedDecoder._pp_blocks`` step (the manual
    pipeline program both compiled phases run) over an ``AbstractMesh``
    stand-in — zero devices, zero compile. Returns ``(label, scope, fn,
    args)`` rows: one prefill-shaped step ([B, S, D] in) and one
    decode-shaped step ([B, 1, D] in). The overlap lint walks these; the
    cost model (costmodel.py) reads collective comm bytes off the same
    traced decode step, so what is linted and what is priced is the one
    program serving would run.

    ``module``/``config`` override the registry stand-in — the cost
    model passes the config actually being scored so the priced
    activations are that model's, not the tiny stand-in's; the overlap
    lint keeps the stand-ins (the property is shape-independent).
    ``mesh`` overrides the AbstractMesh stand-in with a CONCRETE mesh:
    bench.py's ICI calibration row compiles the returned decode step on
    real devices and compares the executable's measured comm bytes
    against the cost model's walk of the same jaxpr."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh

    from llm_sharding_demo_tpu.models.llama import LlamaConfig
    from llm_sharding_demo_tpu.parallel import partition as Pt
    from llm_sharding_demo_tpu.parallel.ppdecode import (
        GRAFTCHECK_DECODE_ENTRY_POINTS, PipelinedDecoder)
    from . import registry

    if module is None or config is None:
        fams = registry.families()
        module, config = fams["llama-tiny" if family == "llama"
                              else "gpt2-tiny"]
    if "_pp_blocks" not in GRAFTCHECK_DECODE_ENTRY_POINTS:
        raise ValueError(
            "ppdecode no longer declares _pp_blocks in "
            "GRAFTCHECK_DECODE_ENTRY_POINTS — update this builder to "
            "trace the declared entry points")
    bounds = Pt.balanced_boundaries(config.n_layer, n_stages)
    specs = Pt.make_stage_specs(config.n_layer, bounds)
    dec = PipelinedDecoder.__new__(PipelinedDecoder)
    dec.config = config
    dec.mesh = mesh if mesh is not None \
        else AbstractMesh((("pp", n_stages),))
    dec.max_seq = max_seq
    dec.pp_axis = "pp"
    dec.n_stages = n_stages
    dec.dtype = jnp.float32
    dec._llama = isinstance(config, LlamaConfig)
    if len({s.n_blocks for s in specs}) == 1:
        dec._valid = None
        dec.per_stage = specs[0].n_blocks
    else:
        dec._valid = Pt.stage_valid_mask(specs)
        dec.per_stage = max(s.n_blocks for s in specs)

    pavals = _param_avals(module, config)
    if dec._valid is None:
        blocks = jax.eval_shape(
            lambda p: Pt.stack_stage_params(p, specs), pavals)
    else:
        blocks = jax.eval_shape(
            lambda p: Pt.stack_stage_params_padded(p, specs)[0], pavals)
    heads = getattr(config, "n_kv_head", config.n_head)
    cache = jax.ShapeDtypeStruct(
        (n_stages, dec.per_stage, batch, heads, max_seq, config.head_dim),
        jnp.float32)
    length = jax.ShapeDtypeStruct((), jnp.int32)

    def step_fn(s: int):
        h = jax.ShapeDtypeStruct((batch, s, config.n_embd), jnp.float32)

        def fn(blocks, ck, cv, h, length):
            return dec._pp_blocks(blocks, ck, cv, h, length)

        return fn, (blocks, cache, cache, h, length)

    rows = []
    for label, s in (("prefill-step", seq), ("decode-step", 1)):
        fn, args = step_fn(s)
        rows.append((f"ppdecode/pp={n_stages}/{label}",
                     "PipelinedDecoder._pp_blocks", fn, args))
    return rows


def check_decode_overlap(n_stages: int, where: str) -> List[Finding]:
    """The registry-driven overlap pass: trace every declared pipelined
    decode program at this stage count and run the overlap rule on it."""
    import jax
    findings: List[Finding] = []
    for label, scope, fn, args in build_ppdecode_programs(n_stages):
        jaxpr = jax.make_jaxpr(fn)(*args)
        findings.extend(check_overlap_jaxpr(
            jaxpr, f"{where}/{label}", _PPDECODE_PATH, scope))
    return findings


# -- paged KV block-table contracts ------------------------------------------

_KV_POOL_PATH = "llm_sharding_demo_tpu/runtime/kv_pool.py"
_PAGED_OPS_PATH = "llm_sharding_demo_tpu/ops/paged_attention.py"


def check_paged_contracts(n_layer: int, num_blocks: int, n_kv_head: int,
                          block_size: int, head_dim: int, max_seq: int,
                          batches: Sequence[int] = (1, 2),
                          where: str = "") -> List[Finding]:
    """The paged block-table contract family, by abstract eval (no
    device, no compile):

    - the pool aval is the declared ``pool_shape`` (per layer
      ``[num_blocks, 2, n_kv_head, block_size, head_dim]`` + the trash
      block);
    - block tables are int32 and ``blocks_per_row * block_size ==
      max_seq`` (the gathered view must equal the engine's compiled
      cache width EXACTLY — any mismatch would silently mint new
      decode programs per width);
    - ``gather_kv`` emits the engine's contiguous cache aval and
      ``scatter_kv(gather_kv(...))`` round-trips the pool aval;
    - ``paged_decode_attention`` preserves the pool aval and emits the
      attention output aval ``[B, H, 1, hd]``.
    """
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.ops import paged_attention as PA
    findings: List[Finding] = []
    try:
        nbm = PA.blocks_per_row(max_seq, block_size)
    except ValueError as e:
        return [Finding("paged-contract", _PAGED_OPS_PATH, 1, where,
                        f"rejected geometry: {e}")]
    pool_aval = jax.ShapeDtypeStruct(
        PA.pool_shape(n_layer, num_blocks, n_kv_head, block_size,
                      head_dim), jnp.float32)
    if pool_aval.shape[1:] != (num_blocks + 1, 2, n_kv_head, block_size,
                               head_dim):
        findings.append(Finding(
            "paged-contract", _PAGED_OPS_PATH, 1, where,
            f"pool aval {pool_aval.shape} breaks the per-layer "
            "[num_blocks+1, 2, n_kv_head, block_size, head_dim] "
            "contract"))
    for b in batches:
        tab = jax.ShapeDtypeStruct((b, nbm), jnp.int32)
        kv = jax.eval_shape(PA.gather_kv, pool_aval, tab)
        want = (n_layer, b, n_kv_head, max_seq, head_dim)
        for name, side in (("k", kv[0]), ("v", kv[1])):
            if tuple(side.shape) != want:
                findings.append(Finding(
                    "paged-contract", _PAGED_OPS_PATH, 1, where,
                    f"gather_kv {name} aval {tuple(side.shape)} != "
                    f"engine cache aval {want} at B={b} — the paged "
                    "path would not share the compiled decode "
                    "programs"))
        rt = jax.eval_shape(PA.scatter_kv, pool_aval, kv[0], kv[1], tab)
        if (tuple(rt.shape) != tuple(pool_aval.shape)
                or rt.dtype != pool_aval.dtype):
            findings.append(Finding(
                "paged-contract", _PAGED_OPS_PATH, 1, where,
                f"scatter(gather(pool)) aval {tuple(rt.shape)}/"
                f"{rt.dtype} does not round-trip the pool aval at "
                f"B={b}"))
        # float block tables must be REJECTED at trace time (a float
        # table would silently truncate placement)
        bad_tab = jax.ShapeDtypeStruct((b, nbm), jnp.float32)
        try:
            jax.eval_shape(PA.gather_kv, pool_aval, bad_tab)
            findings.append(Finding(
                "paged-contract", _PAGED_OPS_PATH, 1, where,
                "gather_kv accepted a float block table — tables must "
                "be int32"))
        except Exception:  # noqa: BLE001 — the rejection IS the contract
            pass
        h = n_kv_head * 2  # a GQA-grouped query head count
        q = jax.ShapeDtypeStruct((b, h, 1, head_dim), jnp.float32)
        knew = jax.ShapeDtypeStruct((b, n_kv_head, 1, head_dim),
                                    jnp.float32)
        out, pool_out = jax.eval_shape(
            lambda q, kn, vn, p, t: PA._paged_decode_attention_impl(
                q, kn, vn, p, t, jnp.int32(0), jnp.int32(4)),
            q, knew, knew, pool_aval, tab)
        if tuple(out.shape) != (b, h, 1, head_dim):
            findings.append(Finding(
                "paged-contract", _PAGED_OPS_PATH, 1, where,
                f"paged_decode_attention out aval {tuple(out.shape)} "
                f"!= {(b, h, 1, head_dim)}"))
        if tuple(pool_out.shape) != tuple(pool_aval.shape):
            findings.append(Finding(
                "paged-contract", _PAGED_OPS_PATH, 1, where,
                "paged_decode_attention does not preserve the pool "
                "aval"))
    return findings


# -- registry-driven pass ----------------------------------------------------


def run_semantic() -> Tuple[List[Finding], int]:
    """All registry contracts; -> (findings, checks_run)."""
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh

    from llm_sharding_demo_tpu.models import is_stage_partitionable
    from llm_sharding_demo_tpu.parallel import spmd
    from . import registry
    findings: List[Finding] = []
    checks = 0

    fams = registry.families()
    for fam_name, (module, config) in fams.items():
        if not is_stage_partitionable(config):
            continue
        for plan_name, bounds in registry.STAGE_PLANS:
            where = f"{fam_name}/{plan_name}"
            for dtype in (jnp.float32,):
                findings.extend(check_stage_contracts(
                    module, config, bounds, where=where, dtype=dtype))
                checks += 1
            from llm_sharding_demo_tpu.parallel import partition as Pt
            specs = Pt.make_stage_specs(config.n_layer, bounds)
            if len({s.n_blocks for s in specs}) > 1:
                findings.extend(check_padded_stacking(
                    module, config, bounds, where=where))
                checks += 1

    # PartitionSpec trees vs the mesh stand-ins they are meant for
    mesh_tp = AbstractMesh(tuple(registry.MESHES["tp2"].items()))
    mesh_ep = AbstractMesh(tuple(registry.MESHES["ep2-tp2"].items()))
    gpt2_mod, gpt2_cfg = fams["gpt2-tiny"]
    llama_mod, llama_cfg = fams["llama-tiny"]
    moe_mod, moe_cfg = fams["moe-tiny"]
    findings.extend(check_pspec_tree(
        spmd.param_pspecs(mesh_tp), _param_avals(gpt2_mod, gpt2_cfg),
        registry.MESHES["tp2"], "gpt2-tiny/tp2"))
    findings.extend(check_pspec_tree(
        spmd.llama_param_pspecs(mesh_tp), _param_avals(llama_mod, llama_cfg),
        registry.MESHES["tp2"], "llama-tiny/tp2"))
    findings.extend(check_pspec_tree(
        spmd.moe_param_pspecs(mesh_ep), _param_avals(moe_mod, moe_cfg),
        registry.MESHES["ep2-tp2"], "moe-tiny/ep2-tp2"))
    checks += 3

    # engine tp divisibility contracts for the registered stand-ins
    tp = registry.MESHES["tp2"]["tp"]
    for name, cfg in (("gpt2-tiny", gpt2_cfg), ("llama-tiny", llama_cfg)):
        kv = getattr(cfg, "n_kv_head", cfg.n_head)
        if cfg.n_head % tp or (kv % tp and kv >= tp):
            findings.append(Finding(
                "pspec", _SPMD_PATH, 1, f"{name}/tp2",
                f"n_head={cfg.n_head}/n_kv_head={kv} not shardable over "
                f"tp={tp} whole heads"))
        checks += 1

    # ppermute ring bijection per registered stage-axis size
    for n in registry.RING_SIZES:
        findings.extend(check_ring_program(n, f"ring/pp={n}"))
        checks += 1

    # paged KV block-table contracts per registered pool geometry
    for label, kwargs in registry.PAGED_GEOMETRIES:
        findings.extend(check_paged_contracts(where=label, **kwargs))
        checks += 1

    # overlap lint over the declared pipelined-decode programs (ROADMAP
    # item 3 seed): the currently-serial ppdecode handoffs surface here
    # and stay baselined with justifications until double-buffering
    # lands — at which point the stale suppressions fail --strict
    for n in registry.OVERLAP_RING_SIZES:
        findings.extend(check_decode_overlap(n, f"overlap/pp={n}"))
        checks += 1

    return findings, checks
