"""Recompile-budget certifier: static program-space bounds per workload.

XLA compiles one program per (static shapes, static args) key, so the
compiled-program population of a serving config is a *function* of the
request stream's shape set — the runtime tests observe it after the fact
via ``jit._cache_size()`` (PR 1's compile-space asserts). This module
computes the same numbers STATICALLY: for each declared jit entry point
(``JIT_ENTRY_POINTS`` in the runtime modules, enforced by the
``undeclared-jit`` lint rule) it derives the program key a call mints,
by running the engine's REAL host-side planning code — never a
re-implementation that could drift:

- ``DecodeEngine._align_chunks`` / ``_segments`` /
  ``_eos_capped_segments`` run against a stand-in carrying only the
  fields they read (``prefill_chunk``, ``max_seq``, ``_decode_kernel``,
  ``WINDOW_BUCKET``), so the certified segment plan IS the executed one;
- static-argument identity uses the live ``SamplingConfig`` equality
  (the jit static-arg hash), with the spec engine's documented
  ``spec=False`` normalization applied where the runtime applies it.

Certified == observed is the acceptance bar: tests/test_graftcheck.py
replays the PR 1 compile-space workloads on real tiny engines and
asserts the bound equals every ``_cache_size()`` exactly — no looser,
no tighter. (One documented exception: an ``eos``-armed call may exit
early, executing a PREFIX of its certified segments — the bound is
then an upper bound, still exact when generation runs to budget.)

Program-key model per entry point:

- ``_prefill``          (batch, padded prompt_len, pad operand present)
- ``_prefill_chunked``  (batch, n_chunks)
- ``_decode_seg``       (batch, segment len, window, sampling,
                         key form [one|per-row], pad operand present)
- ``_loop``   [spec]    (max_new, normalized sampling, pad present)
- ``_loop_b`` [spec]    (batch, max_new, normalized sampling)
- ``_seg_b``  [spec]    (width, max_verify, normalized sampling)
"""

from __future__ import annotations

import dataclasses
import types
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class EngineDesc:
    """The DecodeEngine fields that shape its program space."""

    max_seq: int
    prefill_chunk: Optional[int] = None
    kernel: bool = False          # a Pallas decode kernel is active
    window_bucket: Optional[int] = None   # None -> engine default


@dataclasses.dataclass(frozen=True)
class SpecDesc:
    draft_len: int
    ngram: int = 2


@dataclasses.dataclass(frozen=True)
class PagedDesc:
    """The KVBlockPool fields that shape its program space (the pool's
    block COUNT never keys programs — tables are traced).

    ``quantized``: the pool stores narrow blocks (``block_dtype`` set),
    so its movers are the ``_gather_q``/``_scatter_q`` family — same
    key structure (tables traced, scales ride the same program), the
    plain movers' bound drops to zero. The STORAGE dtype itself never
    keys programs either: int8 vs fp8 pools mint the same key set.
    """

    max_seq: int
    block_size: int
    quantized: bool = False

    @property
    def nbm(self) -> int:
        return self.max_seq // self.block_size


@dataclasses.dataclass(frozen=True)
class GenerateCall:
    """One ``generate()`` invocation, by shape."""

    prompt_lens: Tuple[int, ...]          # one entry per row
    max_new: int
    sampling: object = None               # SamplingConfig; None -> greedy
    per_row_keys: bool = False            # [B, 2] key stack passed
    explicit_pad: Optional[Tuple[int, ...]] = None
    eos: bool = False


def greedy_sampling():
    from llm_sharding_demo_tpu.runtime.engine import SamplingConfig
    return SamplingConfig()


def _planner(desc: EngineDesc):
    """Stand-in carrying exactly the fields the engine's host-side
    planning methods read — the methods themselves are borrowed from
    ``DecodeEngine`` unbound, so the certified plan is computed by THE
    production planning code."""
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    return types.SimpleNamespace(
        prefill_chunk=desc.prefill_chunk,
        max_seq=desc.max_seq,
        _decode_kernel="device" if desc.kernel else None,
        WINDOW_BUCKET=desc.window_bucket or DecodeEngine.WINDOW_BUCKET)


def _prepare(call: GenerateCall):
    """Mirror ``prepare_generate``'s shape outcome: [B, S] left-padded
    ids + per-row pad vector."""
    import numpy as np
    lens = call.prompt_lens
    b, s = len(lens), max(lens)
    if call.explicit_pad is not None:
        pad = np.asarray(call.explicit_pad, dtype=np.int32)
    else:
        pad = np.asarray([s - l for l in lens], dtype=np.int32)
    return np.zeros((b, s), dtype=np.int32), pad, b, s


def _sampling(call: GenerateCall):
    return call.sampling if call.sampling is not None else greedy_sampling()


def engine_call_keys(desc: EngineDesc, call: GenerateCall) -> Dict[str, set]:
    """Program keys one ``DecodeEngine.generate`` call touches."""
    from llm_sharding_demo_tpu.runtime.engine import (DecodeEngine,
                                                      _eos_capped_segments)
    ns = _planner(desc)
    ids, pad, b, s = _prepare(call)
    ids, pad, plen, chunk = DecodeEngine._align_chunks(
        ns, ids, pad, s, reserve=call.max_new)
    pad_any = bool(pad.any())
    keys: Dict[str, set] = {"_prefill": set(), "_prefill_chunked": set(),
                            "_decode_seg": set()}
    if chunk:
        keys["_prefill_chunked"].add((b, ids.shape[1] // chunk))
    else:
        keys["_prefill"].add((b, plen, pad_any))
    if call.max_new > 1:
        segs = DecodeEngine._segments(ns, plen, call.max_new)
        if call.eos:
            segs = _eos_capped_segments(segs)
        key_form = "per-row" if call.per_row_keys else "one"
        for n, window in segs:
            keys["_decode_seg"].add(
                (b, n, window, _sampling(call), key_form, pad_any))
    return keys


def spec_call_keys(desc: EngineDesc, spec: SpecDesc,
                   call: GenerateCall) -> Dict[str, set]:
    """Program keys one ``SpecDecodeEngine.generate`` call touches
    (prefill shared with the wrapped plain engine; the verify loop
    replaces the decode scan)."""
    import dataclasses as dc

    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    ns = _planner(desc)
    ids, pad, b, s = _prepare(call)
    ids, pad, plen, chunk = DecodeEngine._align_chunks(
        ns, ids, pad, s, reserve=call.max_new + spec.draft_len)
    pad_any = bool(pad.any())
    norm = dc.replace(_sampling(call), spec=False)
    keys: Dict[str, set] = {"_prefill": set(), "_prefill_chunked": set(),
                            "_loop": set(), "_loop_b": set()}
    if chunk:
        keys["_prefill_chunked"].add((b, ids.shape[1] // chunk))
    else:
        keys["_prefill"].add((b, plen, pad_any))
    if b == 1:
        keys["_loop"].add((call.max_new, norm, pad_any))
    else:
        keys["_loop_b"].add((b, call.max_new, norm))
    return keys


def paged_runner_keys(desc: EngineDesc, paged: PagedDesc,
                      call: GenerateCall) -> Dict[str, set]:
    """Program keys one ``PagedKVRunner.generate`` call touches: the
    engine's own prefill/decode keys (the paged path runs THE same
    compiled programs on gathered views — that identity is the
    byte-equality argument) plus the pool's data movers:

    - ``_gather``/``_scatter``: one program per (batch, table width) —
      tables and block ids are traced operands, so PLACEMENT never
      keys anything;
    - ``_scatter`` additionally mints one program per shared-prefix
      column offset (the narrower owned-tail view after a store hit —
      placement AND the decode loop's per-segment write-back both use
      it: shared registry blocks are immutable, so decode scatters only
      the owned columns); plain runs stay on the full-width key;
    - ``_scatter_row``/``_copy``: admission/CoW movers — unused by a
      plain generate (the iteration scheduler and prefix sharing mint
      them), so their bound here is zero;
    - a QUANTIZED pool (``paged.quantized``) runs the ``_q`` mover
      family instead — identical key structure (the scales array rides
      the same program; tables stay traced), with the plain movers
      bounded at zero.
    """
    keys = engine_call_keys(desc, call)
    b = len(call.prompt_lens)
    gather = "_gather_q" if paged.quantized else "_gather"
    scatter = "_scatter_q" if paged.quantized else "_scatter"
    row = "_scatter_row_q" if paged.quantized else "_scatter_row"
    copy = "_copy_q" if paged.quantized else "_copy"
    keys[gather] = ({(b, paged.nbm)} if call.max_new > 1 else set())
    keys[scatter] = {(b, paged.nbm)}
    keys[row] = set()
    keys[copy] = set()
    return keys


def certify_paged(desc: EngineDesc, paged: PagedDesc,
                  calls: Sequence[GenerateCall]) -> Dict[str, int]:
    """Distinct-program bound per entry point for a paged workload."""
    pools: Dict[str, set] = {}
    for call in calls:
        for name, ks in paged_runner_keys(desc, paged, call).items():
            pools.setdefault(name, set()).update(ks)
    return {name: len(ks) for name, ks in pools.items()}


def iter_spec_segment_keys(spec: SpecDesc, seg_steps: int,
                           widths: Iterable[int],
                           samplings: Iterable[object]) -> set:
    """``_seg_b`` program keys the iteration scheduler mints: one per
    (compiled width, max_verify, normalized policy) — acceptance counts
    and budgets are traced values and never key programs
    (runtime.iterbatch module docstring)."""
    import dataclasses as dc
    max_verify = max(1, seg_steps // (spec.draft_len + 1))
    return {(w, max_verify, dc.replace(s, spec=False))
            for w in widths for s in samplings}


def certify(desc: EngineDesc, calls: Sequence[GenerateCall],
            spec: Optional[SpecDesc] = None,
            spec_calls: Sequence[GenerateCall] = (),
            ) -> Dict[str, int]:
    """Distinct-program bound per entry point for a workload: the union
    of every call's key set. ``calls`` go through the plain engine,
    ``spec_calls`` through a speculative engine sharing the same
    ``desc`` (prefill programs pool, exactly as the runtime shares
    them)."""
    pools: Dict[str, set] = {}

    def merge(keysets: Dict[str, set]):
        for name, ks in keysets.items():
            pools.setdefault(name, set()).update(ks)

    for call in calls:
        merge(engine_call_keys(desc, call))
    for call in spec_calls:
        if spec is None:
            raise ValueError("spec_calls passed without a SpecDesc")
        merge(spec_call_keys(desc, spec, call))
    return {name: len(ks) for name, ks in pools.items()}


def planner_invariants(desc: EngineDesc, call: GenerateCall) -> List[str]:
    """Static sanity of the segment plan itself (CLI self-check): step
    conservation and window monotonicity/bounds. A violation means the
    planner would mint programs the budget math cannot describe."""
    from llm_sharding_demo_tpu.runtime.engine import (DecodeEngine,
                                                      _eos_capped_segments)
    ns = _planner(desc)
    ids, pad, b, s = _prepare(call)
    # validate the plan the engine would EXECUTE: segments derive from
    # the chunk-aligned prompt length, exactly as in engine_call_keys
    _, _, plen, _ = DecodeEngine._align_chunks(
        ns, ids, pad, s, reserve=call.max_new)
    problems: List[str] = []
    if call.max_new <= 1:
        return problems
    segs = DecodeEngine._segments(ns, plen, call.max_new)
    if call.eos:
        segs = _eos_capped_segments(segs)
    total = sum(n for n, _ in segs)
    if total != call.max_new - 1:
        problems.append(
            f"segment plan covers {total} steps, want {call.max_new - 1} "
            f"(prompt_len={s}, max_new={call.max_new})")
    last_w = 0
    for n, w in segs:
        if n < 1:
            problems.append(f"empty segment in plan {segs}")
        if w is not None:
            if w > desc.max_seq:
                problems.append(f"window {w} exceeds max_seq={desc.max_seq}")
            if w < last_w:
                problems.append(f"windows shrink in plan {segs}")
            last_w = w
    return problems
