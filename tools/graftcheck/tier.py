"""graftcheck tier pass: storage-tier movement discipline
(compile-free).

grafttier (``llm_sharding_demo_tpu/runtime/kv_tier.py``) moves KV
blocks between storage tiers — device pool down to host RAM on cold
pressure, host back to device on an affinity hit. Every movement is a
custody transfer across THREE bookkeeping systems at once: the
graftsan refcount tables, the graftmem byte ledger, and the grafttime
causal stream. A movement site outside the declared boundary can be
individually correct and still leave one of the three silently wrong
— which is why the boundary is a declaration this pass can hold the
tree to, not a convention.

In-file declarations (the registration-annotation idiom of
``POOL_MOVER_SCOPES`` / ``HANDOFF_SCOPES`` / ``MEMORY_LEDGER``):

- ``TIER_POLICY``: ``{tier: {below, budget, eviction, holding,
  component, demote_event, promote_event}}`` — the storage tiers a
  module owns: what each sits below, the env knob bounding it, its
  final-eviction policy, the attribute holding spilled bytes, the
  graftmem component those bytes attribute to, and the grafttime
  event kinds its movements emit. A nested dict literal on purpose —
  statically readable, like ``FAULT_POLICY``.
- ``SPILL_SCOPES``: tuple of function qualnames allowed to invoke
  tier movement (``demote_lru`` / ``promote`` / ``spill_blocks`` /
  ``fill_blocks`` on a tier/pool receiver). Declared per module, the
  way ``HANDOFF_SCOPES`` enumerates the adoption boundary.

Rules (ids in brackets; suppressions ride the shared baseline):

- [undeclared-tier-movement] a tier-movement call in a runtime/
                             module outside any declared SPILL_SCOPES
                             scope (or in a module declaring none) —
                             custody moved between tiers off the
                             reviewed boundary; plus a declared scope
                             invoking no movement (stale).
- [tier-ledger-gap]          a malformed TIER_POLICY; a tier missing
                             a required key; a declared component
                             outside ``graftmem.MEMORY_COMPONENTS``;
                             a tier whose ``holding`` is absent from
                             the module's MEMORY_LEDGER or attributed
                             to a different component there — host
                             bytes the /debug/memory ledger cannot
                             see or double-books.
- [tier-event-drift]         a declared demote/promote event kind
                             outside the grafttime ``EVENT_KINDS``
                             vocabulary, or one with no
                             ``grafttime.emit`` site inside the
                             module's declared SPILL_SCOPES — tier
                             movement invisible to the causal stream.

``--strict`` additionally fails a VACUOUS pass (a module declaring
TIER_POLICY none of whose spill scopes make a live movement call —
the tier boundary went dark); ``cli.run --json`` carries
``tier_checks`` / ``tier_policies`` / ``tier_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _dotted, _module_assign, _parents, _scope_of
from .memory import _declared_dict

TIER_RULE_IDS = ("undeclared-tier-movement", "tier-ledger-gap",
                 "tier-event-drift")

# movement calls are only meaningful where tiers live; serving/ wires
# tiers up (attach_tier) but never moves blocks itself
_RUNTIME_PREFIX = "llm_sharding_demo_tpu/runtime/"

# the movement vocabulary: demote/promote are the tier's own verbs,
# spill/fill are the pool's raw-plane halves they are built from
_MOVEMENT_NAMES = ("demote_lru", "promote", "spill_blocks",
                   "fill_blocks")

# every TIER_POLICY tier must answer all of these (a tier with no
# declared budget or eviction policy is an unbounded cache with extra
# steps)
_REQUIRED_KEYS = ("below", "budget", "eviction", "holding",
                  "component", "demote_event", "promote_event")


def _tierish(recv: Optional[str]) -> bool:
    """Receiver filter: ``tier`` / ``self.tier`` / ``pool`` /
    ``self._pool`` — movement verbs on unrelated receivers (a queue's
    ``promote``) are not tier traffic."""
    if not recv:
        return False
    last = recv.rpartition(".")[2].lstrip("_")
    return "tier" in last or "pool" in last


def _policy_dict(stmt: ast.Assign
                 ) -> Optional[Dict[str, Tuple[Dict[str, str], int]]]:
    """TIER_POLICY nested dict literal ->
    {tier: ({key: value}, line)}; None when not statically readable
    string->dict-of-strings."""
    node = stmt.value
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, Tuple[Dict[str, str], int]] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and isinstance(v, ast.Dict)):
            return None
        entry = _declared_dict(ast.Assign(targets=[], value=v))
        if entry is None:
            return None
        out[k.value] = ({key: val for key, val, _ in entry}, k.lineno)
    return out


def _movement_calls(mod: L.ModuleInfo,
                    parents) -> List[Tuple[int, str, str]]:
    """[(line, enclosing scope, verb)] for tier-movement calls on
    tier/pool receivers."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MOVEMENT_NAMES):
            continue
        if _tierish(_dotted(node.func.value)):
            out.append((node.lineno, _scope_of(node, parents, mod),
                        node.func.attr))
    return out


def _emit_sites(mod: L.ModuleInfo, parents) -> List[Tuple[int, str, str]]:
    """[(line, enclosing scope, kind)] for ``grafttime.emit("<kind>",
    ...)`` sites with a literal kind."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d != "grafttime.emit":
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.append((node.lineno, _scope_of(node, parents, mod),
                        node.args[0].value))
    return out


def run_tier(root: str, paths: Optional[List[str]] = None,
             components: Optional[Dict[str, str]] = None,
             event_kinds: Optional[Dict[str, str]] = None,
             ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``tier_checks`` (declarations + movement/emit sites
    examined — the vacuity guard on the pass itself),
    ``tier_policies`` (per-module count of declared spill scopes with
    a live movement call) and ``vacuous`` (modules whose TIER_POLICY
    matches no live spill scope — the strict driver fails these).
    ``components`` / ``event_kinds`` are injectable for rule fixtures;
    by default the real ``graftmem.MEMORY_COMPONENTS`` /
    ``grafttime.EVENT_KINDS``."""
    if components is None:
        from llm_sharding_demo_tpu.utils import graftmem as GM
        components = GM.MEMORY_COMPONENTS
    if event_kinds is None:
        from llm_sharding_demo_tpu.utils import grafttime as GT
        event_kinds = GT.EVENT_KINDS

    findings: List[Finding] = []
    checks = 0
    policies_live: Dict[str, int] = {}
    vacuous: List[str] = []

    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        in_runtime = mod.relpath.startswith(_RUNTIME_PREFIX)
        policy_stmt = _module_assign(mod, "TIER_POLICY")
        scopes_stmt = _module_assign(mod, "SPILL_SCOPES")
        parents = _parents(mod.tree)
        moves = _movement_calls(mod, parents) if in_runtime else []
        if policy_stmt is None and scopes_stmt is None and not moves:
            continue
        checks += 1

        declared_scopes: Optional[Set[str]] = None
        scopes_line = 0
        if scopes_stmt is not None:
            scopes_line = scopes_stmt.lineno
            declared_scopes = L._string_tuple(scopes_stmt.value)
            if declared_scopes is None:
                findings.append(Finding(
                    "undeclared-tier-movement", mod.relpath,
                    scopes_line, "<module>",
                    "SPILL_SCOPES must be a tuple of string function "
                    "qualnames (the tier pass reads it statically)"))
                declared_scopes = set()

        # -- movement calls vs the declared boundary ----------------------
        live_scopes: Set[str] = set()
        for line, scope, verb in moves:
            checks += 1
            if declared_scopes is None:
                findings.append(Finding(
                    "undeclared-tier-movement", mod.relpath, line,
                    scope,
                    f"tier-movement call ``{verb}`` in a module "
                    "declaring no SPILL_SCOPES — custody crossed a "
                    "storage tier off the reviewed boundary (declare "
                    "the scope beside JIT_ENTRY_POINTS)"))
            elif scope not in declared_scopes:
                findings.append(Finding(
                    "undeclared-tier-movement", mod.relpath, line,
                    scope,
                    f"tier-movement call ``{verb}`` in {scope!r}, "
                    "which SPILL_SCOPES does not declare — demotion/"
                    "promotion outside the declared tier boundary"))
            else:
                live_scopes.add(scope)
        if declared_scopes is not None:
            for scope in sorted(declared_scopes - live_scopes):
                checks += 1
                findings.append(Finding(
                    "undeclared-tier-movement", mod.relpath,
                    scopes_line, scope,
                    f"SPILL_SCOPES declares {scope!r} but it invokes "
                    "no tier movement (stale declaration)"))

        # -- the policy's three-ledger cross-checks -----------------------
        if policy_stmt is None:
            continue
        policy = _policy_dict(policy_stmt)
        if policy is None:
            findings.append(Finding(
                "tier-ledger-gap", mod.relpath, policy_stmt.lineno,
                "<module>",
                "TIER_POLICY must be a dict literal of string tier -> "
                "{string key: string value} (the tier pass reads it "
                "statically)"))
            continue

        ledger_stmt = _module_assign(mod, "MEMORY_LEDGER")
        ledger: Dict[str, str] = {}
        if ledger_stmt is not None:
            entries = _declared_dict(ledger_stmt)
            if entries is not None:
                ledger = {k: v for k, v, _ in entries}

        emits = _emit_sites(mod, parents)
        emitted_in_scope = {kind for _, scope, kind in emits
                            if declared_scopes and scope
                            in declared_scopes}
        checks += len(emits)

        for tier, (entry, line) in sorted(policy.items()):
            checks += 1
            missing = [k for k in _REQUIRED_KEYS if k not in entry]
            if missing:
                findings.append(Finding(
                    "tier-ledger-gap", mod.relpath, line, "<module>",
                    f"TIER_POLICY tier {tier!r} is missing required "
                    f"key(s) {missing} — a tier without a declared "
                    "budget/eviction/holding is an unbounded cache "
                    "with extra steps"))
                continue
            if entry["component"] not in components:
                findings.append(Finding(
                    "tier-ledger-gap", mod.relpath, line, "<module>",
                    f"TIER_POLICY tier {tier!r} attributes to "
                    f"component {entry['component']!r}, outside the "
                    f"graftmem vocabulary ({sorted(components)}) — a "
                    "new residency class is a reviewed "
                    "graftmem.MEMORY_COMPONENTS change"))
            holding = entry["holding"]
            if holding not in ledger:
                findings.append(Finding(
                    "tier-ledger-gap", mod.relpath, line, "<module>",
                    f"TIER_POLICY tier {tier!r} spills into holding "
                    f"{holding!r}, absent from this module's "
                    "MEMORY_LEDGER — host bytes the /debug/memory "
                    "ledger cannot attribute"))
            elif ledger[holding] != entry["component"]:
                findings.append(Finding(
                    "tier-ledger-gap", mod.relpath, line, "<module>",
                    f"TIER_POLICY tier {tier!r} attributes "
                    f"{holding!r} to {entry['component']!r} but "
                    f"MEMORY_LEDGER declares {ledger[holding]!r} — "
                    "the tier and the byte ledger disagree about the "
                    "same bytes"))
            for ev_key in ("demote_event", "promote_event"):
                checks += 1
                kind = entry[ev_key]
                if kind not in event_kinds:
                    findings.append(Finding(
                        "tier-event-drift", mod.relpath, line,
                        "<module>",
                        f"TIER_POLICY tier {tier!r} declares "
                        f"{ev_key}={kind!r}, outside the grafttime "
                        "EVENT_KINDS vocabulary — a movement event "
                        "the causal stream cannot carry"))
                elif kind not in emitted_in_scope:
                    findings.append(Finding(
                        "tier-event-drift", mod.relpath, line,
                        "<module>",
                        f"TIER_POLICY tier {tier!r} declares "
                        f"{ev_key}={kind!r} but no grafttime.emit"
                        f"({kind!r}, ...) site lives inside a "
                        "declared SPILL_SCOPES scope — tier movement "
                        "invisible to the timeline"))

        policies_live[mod.relpath] = len(live_scopes)
        if not live_scopes:
            vacuous.append(mod.relpath)

    summary = {
        "tier_checks": checks,
        "tier_policies": policies_live,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
