"""SARIF 2.1.0 emitter for the verifier payload (``--sarif``).

One run, one driver (``graftcheck``), one rule per distinct finding
rule id, one result per finding with a ``file:line`` physical
location. Baseline-suppressed findings are NOT dropped: they ride
along as results carrying a ``suppressions`` entry (kind
``external``, the baseline justification as the note), which is how
SARIF viewers and code-scanning UIs render "known, accepted" — the
same information the text mode folds into the ``N baselined``
counter. The schema pin (``$schema``/``version`` and the result
shape) is tested in tests/test_graftcheck.py.
"""

from __future__ import annotations

from typing import Dict, List

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
SARIF_VERSION = "2.1.0"


def _result(f: dict, suppressed: bool) -> dict:
    out = {
        "ruleId": f["rule"],
        "level": "note" if suppressed else "error",
        "message": {"text": f["message"]},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f["path"]},
                "region": {"startLine": max(1, int(f["line"]))},
            },
        }],
    }
    if suppressed:
        out["suppressions"] = [{
            "kind": "external",
            "justification": f.get("justification", ""),
        }]
    return out


def to_sarif(payload: dict) -> dict:
    """``cli.run``'s payload -> one SARIF 2.1.0 document."""
    results: List[dict] = []
    rules: Dict[str, dict] = {}
    for f in payload.get("findings", ()):
        rules.setdefault(f["rule"], {"id": f["rule"]})
        results.append(_result(f, suppressed=False))
    for f in payload.get("suppressed_findings", ()):
        rules.setdefault(f["rule"], {"id": f["rule"]})
        results.append(_result(f, suppressed=True))
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftcheck",
                "rules": [rules[k] for k in sorted(rules)],
            }},
            "results": results,
        }],
    }
