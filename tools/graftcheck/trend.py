"""graftcheck trend pass: declared-watch static analysis (compile-free).

The grafttrend reducer (``llm_sharding_demo_tpu/utils/grafttrend.py``)
evaluates DECLARED ``WATCH_POLICY = {watch: (series, window, threshold,
severity)}`` contracts over the live telemetry — and a declared SLO is
only a live promise if something watches its burn, while a declared
watch is only an alarm if its series actually exists and is emitted.
This pass (the static half of grafttrend, riding ``python -m
tools.graftcheck`` and the strict in-suite driver — the same
static+dynamic split as graftsan/graftlock/graftload/graftwatch/
graftmem/graftshard, applied at the TREND level) holds the two
declarations to each other:

In-file declarations (the registration-annotation idiom):

- ``WATCH_POLICY``: ``{watch: (series, window, threshold, severity)}``
  — the live watch contract (``utils/grafttrend.py``). ``window`` is a
  ``(short_ms, long_ms)`` pair for burn watches (SLO source series)
  and a single ``window_ms`` for drift/level watches; ``severity`` is
  from the fixed ``page``/``ticket`` vocabulary.
- ``DERIVED_SERIES``: ``{series: provenance}`` — trend inputs COMPUTED
  from producer pairs (graftmem measured-vs-modeled drift, refit
  weight drift) rather than emitted as catalog metrics. The same
  drift class bench_diff gates between runs; a declared derived
  series is only honest if a live watch consumes it.
- ``SIZING_POLICY``: ``{knob: (source_series, min_scale, max_scale)}``
  — the between-waves sizing contract the switcher applies.
- ``SLO_POLICY`` / ``SLO_SOURCE_METRICS`` (``loadgen/profiles.py``):
  read for coverage — every declared SLO metric's source series must
  be watched live.

Rules (ids in brackets; suppressions ride the shared baseline):

- [slo-without-watch]     an SLO_POLICY metric whose source series no
                          WATCH_POLICY entry covers (a declared
                          service promise nobody watches burn on), or
                          a declared DERIVED_SERIES / SIZING_POLICY
                          source no watch consumes (a dead derived
                          declaration — the bench_diff-gated drift
                          class with no live watch).
- [watch-without-source]  a watch on a series that is neither in
                          METRIC_CATALOG nor declared in
                          DERIVED_SERIES (unknown), one on a RETIRED
                          metric (stale — the replacement is spelled
                          out), or one on a catalog series no
                          production call site ever emits — an alarm
                          wired to a wire nobody energizes.
- [malformed-watch]       a WATCH_POLICY that is not a dict literal,
                          an entry that is not a (series, window,
                          threshold, severity) literal 4-tuple, a burn
                          watch without a (short < long) window pair,
                          a drift/level watch without a single
                          positive window, a non-positive threshold,
                          or a severity outside the vocabulary.

``--strict`` additionally fails a VACUOUS pass (a module declaring
WATCH_POLICY whose valid entries cover zero SLO source series — the
contract stopped seeing the promises); ``cli.run --json`` carries
``trend_checks`` / ``trend_policies`` / ``trend_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign
from .slo import _emitted_metric_names, _str_dict_keys

TREND_RULE_IDS = ("slo-without-watch", "watch-without-source",
                  "malformed-watch")

# the fixed severity vocabulary (utils/grafttrend.py SEVERITIES mirrors
# this — tests pin the two stay equal)
TREND_SEVERITIES = ("page", "ticket")


def _num(node: ast.AST) -> Optional[float]:
    """Positive-number constant value, else None."""
    if isinstance(node, ast.Constant) \
            and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool) and node.value > 0:
        return float(node.value)
    return None


def _watch_entry(node: ast.AST):
    """``(series, window, threshold, severity)`` literal 4-tuple ->
    parsed values (window as a float or (short, long) tuple), else
    None. Shape only — mode-dependent window arity is checked by the
    caller, which knows the series classification."""
    if not isinstance(node, (ast.Tuple, ast.List)) \
            or len(node.elts) != 4:
        return None
    series_n, window_n, thresh_n, sev_n = node.elts
    if not (isinstance(series_n, ast.Constant)
            and isinstance(series_n.value, str) and series_n.value):
        return None
    if isinstance(window_n, (ast.Tuple, ast.List)):
        parts = [_num(e) for e in window_n.elts]
        if len(parts) != 2 or any(p is None for p in parts):
            return None
        window: object = (parts[0], parts[1])
    else:
        window = _num(window_n)
        if window is None:
            return None
    threshold = _num(thresh_n)
    if threshold is None:
        return None
    if not (isinstance(sev_n, ast.Constant)
            and isinstance(sev_n.value, str)):
        return None
    return series_n.value, window, threshold, sev_n.value


def run_trend(root: str, paths: Optional[List[str]] = None,
              catalog: Optional[Dict[str, str]] = None,
              emitted: Optional[Set[str]] = None,
              retired: Optional[Dict[str, str]] = None,
              ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``trend_checks`` (declarations + coverage resolutions
    validated — the vacuity guard on the pass itself),
    ``trend_policies`` (per-module valid watch count) and ``vacuous``
    (modules whose WATCH_POLICY covers no SLO source series — the
    strict driver fails these). ``catalog``/``emitted``/``retired``
    are injectable for rule fixtures; by default the real
    METRIC_CATALOG / RETIRED_METRICS and the scanned production
    emission sites."""
    if catalog is None:
        from llm_sharding_demo_tpu.utils.metrics import METRIC_CATALOG
        catalog = METRIC_CATALOG
    if retired is None:
        from llm_sharding_demo_tpu.utils.metrics import RETIRED_METRICS
        retired = RETIRED_METRICS
    if emitted is None:
        emitted = _emitted_metric_names(root, paths=paths)

    findings: List[Finding] = []
    checks = 0
    policies: Dict[str, int] = {}
    vacuous: List[str] = []

    # pass 1: collect every declaration (watches may live in one
    # module, the SLO promises they must cover in another)
    slo_sources: Dict[str, str] = {}          # metric -> series
    slo_metrics: Dict[str, Tuple[str, int]] = {}   # metric -> decl site
    watches: Dict[str, Tuple[str, str, int, object, float, str]] = {}
    watched_series: Set[str] = set()
    derived: Dict[str, Tuple[str, int]] = {}  # series -> decl site
    sizing: Dict[str, Tuple[str, str, int]] = {}   # knob -> (series, site)
    watch_modules: List[Tuple[object, ast.stmt]] = []

    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        src_stmt = _module_assign(mod, "SLO_SOURCE_METRICS")
        if src_stmt is not None:
            entries = _str_dict_keys(src_stmt.value) or []
            for metric, v in entries:
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    slo_sources[metric] = v.value
        slo_stmt = _module_assign(mod, "SLO_POLICY")
        if slo_stmt is not None:
            for _profile, policy_node in (
                    _str_dict_keys(slo_stmt.value) or []):
                for metric, _tgt in _str_dict_keys(policy_node) or []:
                    slo_metrics.setdefault(
                        metric, (mod.relpath, slo_stmt.lineno))
        der_stmt = _module_assign(mod, "DERIVED_SERIES")
        if der_stmt is not None:
            for series, _prov in _str_dict_keys(der_stmt.value) or []:
                derived.setdefault(
                    series, (mod.relpath, der_stmt.lineno))
        siz_stmt = _module_assign(mod, "SIZING_POLICY")
        if siz_stmt is not None:
            for knob, v in _str_dict_keys(siz_stmt.value) or []:
                if isinstance(v, (ast.Tuple, ast.List)) and v.elts \
                        and isinstance(v.elts[0], ast.Constant) \
                        and isinstance(v.elts[0].value, str):
                    sizing[knob] = (v.elts[0].value, mod.relpath,
                                    siz_stmt.lineno)
        watch_stmt = _module_assign(mod, "WATCH_POLICY")
        if watch_stmt is not None:
            watch_modules.append((mod, watch_stmt))

    slo_series = {slo_sources[m] for m in slo_metrics
                  if m in slo_sources}

    # pass 2: validate each WATCH_POLICY declaration
    for mod, stmt in watch_modules:
        checks += 1
        line = stmt.lineno
        decl = _str_dict_keys(stmt.value)
        if decl is None:
            findings.append(Finding(
                "malformed-watch", mod.relpath, line, "<module>",
                "WATCH_POLICY must be a dict literal {watch: (series, "
                "window, threshold, severity)} — the trend pass reads "
                "it statically"))
            policies[mod.relpath] = 0
            vacuous.append(mod.relpath)
            continue
        valid = 0
        covered: Set[str] = set()
        for watch, entry_node in decl:
            checks += 1
            parsed = _watch_entry(entry_node)
            if parsed is None:
                findings.append(Finding(
                    "malformed-watch", mod.relpath, line, watch,
                    f"watch {watch!r}: entry must be a literal "
                    "(series, window, threshold, severity) 4-tuple "
                    "with a non-empty series string, positive "
                    "window(s)/threshold, and a string severity"))
                continue
            series, window, threshold, severity = parsed
            if severity not in TREND_SEVERITIES:
                findings.append(Finding(
                    "malformed-watch", mod.relpath, line, watch,
                    f"watch {watch!r}: severity {severity!r} outside "
                    f"the vocabulary {TREND_SEVERITIES}"))
                continue
            is_burn = series in set(slo_sources.values())
            if is_burn:
                if not (isinstance(window, tuple)
                        and window[0] < window[1]):
                    findings.append(Finding(
                        "malformed-watch", mod.relpath, line, watch,
                        f"watch {watch!r}: a burn watch on SLO source "
                        f"series {series!r} needs a (short_ms, "
                        "long_ms) window pair with short < long — "
                        "multi-window burn-rate is the declared "
                        "alerting rule"))
                    continue
            elif isinstance(window, tuple):
                findings.append(Finding(
                    "malformed-watch", mod.relpath, line, watch,
                    f"watch {watch!r}: {series!r} is not an SLO "
                    "source series; drift/level watches take a single "
                    "window_ms, not a window pair"))
                continue
            if series in retired:
                findings.append(Finding(
                    "watch-without-source", mod.relpath, line, watch,
                    f"watch {watch!r} names RETIRED metric {series!r} "
                    f"(stale declaration) — use {retired[series]}"))
                continue
            if series not in catalog and series not in derived:
                findings.append(Finding(
                    "watch-without-source", mod.relpath, line, watch,
                    f"watch {watch!r} names series {series!r}, which "
                    "is neither in METRIC_CATALOG nor declared in "
                    "DERIVED_SERIES — an alarm on a series that does "
                    "not exist"))
                continue
            if series in catalog and series not in emitted:
                findings.append(Finding(
                    "watch-without-source", mod.relpath, line, watch,
                    f"watch {watch!r} names catalog series {series!r}, "
                    "which no production call site emits — a watch on "
                    "a silent series can never trip OR clear"))
                continue
            valid += 1
            covered.add(series)
            watches[watch] = (mod.relpath, series, line, window,
                              threshold, severity)
            watched_series.add(series)
        policies[mod.relpath] = valid
        if not covered & slo_series:
            vacuous.append(mod.relpath)

    # pass 3: coverage — every declared SLO metric's source series must
    # have a live watch; every derived/sizing source must be consumed
    anchor = (watch_modules[0][0].relpath, watch_modules[0][1].lineno) \
        if watch_modules else None
    for metric in sorted(slo_metrics):
        source = slo_sources.get(metric)
        if source is None:
            continue   # the slo pass owns the missing-mapping finding
        checks += 1
        if source in watched_series:
            continue
        where = anchor if anchor is not None else slo_metrics[metric]
        findings.append(Finding(
            "slo-without-watch", where[0], where[1], metric,
            f"SLO metric {metric!r} (source series {source!r}) has no "
            "live WATCH_POLICY entry — a declared service promise "
            "nobody watches burn on is only discovered at the next "
            "bench run"))
    for series in sorted(derived):
        checks += 1
        if series in watched_series:
            continue
        where = derived[series]
        findings.append(Finding(
            "slo-without-watch", where[0], where[1], series,
            f"DERIVED_SERIES declares {series!r} but no WATCH_POLICY "
            "entry consumes it — a dead measured-vs-modeled "
            "declaration (the bench_diff-gated drift class must be "
            "watched live)"))
    for knob in sorted(sizing):
        series, relpath, line = sizing[knob]
        checks += 1
        if series in catalog or series in derived:
            continue
        findings.append(Finding(
            "watch-without-source", relpath, line, knob,
            f"SIZING_POLICY knob {knob!r} reads series {series!r}, "
            "which is neither in METRIC_CATALOG nor declared in "
            "DERIVED_SERIES — the sizer would scale from a series "
            "that does not exist"))

    summary = {
        "trend_checks": checks,
        "trend_policies": policies,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
