"""graftcheck faults pass: fault-contract static analysis (compile-free).

The serving topology is coordinator-plus-shards (generalizing, per
ROADMAP item 2, to a disaggregated fleet), and at fleet scale failure is
steady state — yet until this pass the repo's failure story was ad-hoc:
one hard-coded ``timeout=30`` hop, bare ``Event.wait``/``Queue.get``
seams, and nothing proving a deadline survives its way downstream.
Mirroring graftsan/graftlock's static+dynamic split, this module is the
STATIC half: every cross-process or host-blocking boundary becomes a
DECLARED contract, enforced by AST rules over the production tree. The
dynamic half — seeded fault injection, deadline budgets, and the
``HopPolicy`` breaker — lives in ``llm_sharding_demo_tpu/utils/
graftfault.py`` (which, like any harness runtime, is excluded from its
own pass's scan).

In-file declaration (the registration-annotation idiom of
``JIT_ENTRY_POINTS`` / ``DONATED_ARGS`` / ``GUARDED_STATE``):

- ``FAULT_POLICY``: dict literal ``{site: (deadline_source,
  retry_class, degradation)}`` — one entry per blocking SITE in the
  module. The site key is the call's trailing dotted form
  (``"requests.post"``, ``"done.wait"``, ``"_queue.get"``,
  ``"proc.wait"``, ``"subprocess.run"``). ``deadline_source`` says what
  bounds the wait and is drawn from a fixed vocabulary:

  * ``"request"``  — the per-request deadline budget: the call MUST
                     carry a timeout argument (and, inside a function
                     that takes a deadline parameter, derive it from
                     the remaining budget — the deadline-drop rule);
  * ``"config"``   — a configured constant budget: a timeout argument
                     is still required at the call;
  * ``"watchdog"`` — an external kill timer bounds the wait (the
                     subproc watchdog): a call-site timeout is not
                     required, the declaration documents the bound;
  * ``"unbounded"``— indefinite by design (an idle worker parked on
                     its queue): allowed, but only as a declared,
                     justified choice.

  ``retry_class`` and ``degradation`` are free-form documentation
  strings ("hop-policy", "none"; "typed-503 + breaker", "cancel at next
  boundary") — the pass validates their presence, humans read them.

Blocking classes the pass recognizes (host fault boundaries only —
``ops/`` is exempt: pallas DMA-semaphore ``.wait()`` is device-side):

- **hop**: ``requests.<verb>(...)`` network round trips;
- **wait**: ``<recv>.wait(...)`` event/process waits;
- **queue-get**: ``<recv>.get(...)`` where the receiver names a queue
  (``self._queue.get``); ``get_nowait`` never blocks and is ignored;
- **subprocess**: ``subprocess.run/call/check_call/check_output`` and
  ``.communicate()``.

Rules (ids in brackets; suppressions ride the shared baseline):

- [bare-blocking-call] a blocking site with no FAULT_POLICY entry (or a
                       module with blocking sites and no declaration at
                       all, or a stale/malformed entry), or a site
                       declared ``request``/``config`` whose call
                       carries no timeout argument.
- [unbounded-retry]    a retry loop (a loop whose body retries a
                       blocking call through a non-re-raising except)
                       with no attempt cap (``while True``) — or a
                       capped loop with no backoff sleep between
                       attempts (hammering a failing dependency at
                       full rate).
- [deadline-drop]      inside a function that accepts a deadline
                       parameter (``deadline``/``deadline_s``/
                       ``deadline_ms``/``budget_s``), a blocking call
                       whose timeout is absent or not DERIVED from the
                       remaining budget (simple assignment taint from
                       the deadline name) — the budget dies at that
                       hop.
- [swallowed-fault]    an except handler around a blocking site whose
                       body only ``pass``es or only logs — the fault
                       boundary exists and its failures vanish.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _dotted, _module_assign, _parents, _scope_of

FAULTS_RULE_IDS = ("bare-blocking-call", "unbounded-retry",
                   "deadline-drop", "swallowed-fault")

# the injection/deadline/breaker runtime is the measurement apparatus
# (same exemption class as graftsched in the locks pass)
_EXEMPT_RELPATHS = {"llm_sharding_demo_tpu/utils/graftfault.py",
                    "llm_sharding_demo_tpu/utils/graftsched.py"}
# pallas DMA-semaphore .wait() in kernels is device-side data movement,
# not a host fault boundary
_EXEMPT_PREFIXES = ("llm_sharding_demo_tpu/ops/",)

_DEADLINE_SOURCES = ("request", "config", "watchdog", "unbounded")
_TIMEOUTLESS_OK = ("watchdog", "unbounded")
_DEADLINE_PARAMS = {"deadline", "deadline_s", "deadline_ms", "budget_s"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output"}
_LOG_RECEIVERS = {"log", "logger", "logging", "warnings"}


# -- declarations -------------------------------------------------------------


def declared_policy(mod: L.ModuleInfo,
                    ) -> Tuple[Optional[Dict[str, tuple]], int,
                               List[str]]:
    """``FAULT_POLICY`` -> ({site: (source, retry, degradation)}, decl
    line, malformed-entry messages); (None, 0, []) when undeclared."""
    stmt = _module_assign(mod, "FAULT_POLICY")
    if stmt is None:
        return None, 0, []
    bad: List[str] = []
    if not isinstance(stmt.value, ast.Dict):
        return {}, stmt.lineno, ["FAULT_POLICY must be a dict literal"]
    out: Dict[str, tuple] = {}
    for k, v in zip(stmt.value.keys, stmt.value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            bad.append("FAULT_POLICY keys must be string site names")
            continue
        vals: Optional[List[str]] = None
        if isinstance(v, (ast.Tuple, ast.List)):
            vals = [e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            if len(vals) != len(v.elts):
                vals = None
        if vals is None or len(vals) != 3:
            bad.append(f"site {k.value!r}: policy must be a "
                       "(deadline_source, retry_class, degradation) "
                       "string triple")
            continue
        if vals[0] not in _DEADLINE_SOURCES:
            bad.append(f"site {k.value!r}: unknown deadline_source "
                       f"{vals[0]!r} (vocabulary: "
                       f"{_DEADLINE_SOURCES})")
            continue
        out[k.value] = tuple(vals)
    return out, stmt.lineno, bad


# -- blocking-site classification ---------------------------------------------


@dataclasses.dataclass
class BlockingSite:
    line: int
    scope: str
    key: str                 # declaration key ("requests.post", ...)
    cls: str                 # hop | wait | queue-get | subprocess
    has_timeout: bool
    timeout_node: Optional[ast.AST]
    node: ast.Call


def _timeout_arg(call: ast.Call, cls: str,
                 ) -> Tuple[bool, Optional[ast.AST]]:
    for kw in call.keywords:
        if kw.arg in ("timeout", "timeout_s"):
            return True, kw.value
    if cls == "wait" and call.args:
        return True, call.args[0]            # Event.wait(t)
    if cls == "queue-get" and len(call.args) >= 2:
        return True, call.args[1]            # Queue.get(block, t)
    return False, None


def classify_call(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(site key, class) when ``call`` is a recognized blocking form."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = _dotted(f.value)
    if recv is None:
        return None
    leaf = recv.rpartition(".")[2]
    if recv == "requests" and not f.attr.startswith("exception"):
        return f"requests.{f.attr}", "hop"
    if leaf == "client" and f.attr in ("post", "get"):
        # a serving-wire client hop (the fleet router's replica
        # dispatch; the loadgen driver's measured request path) — the
        # in-process TestClient and a requests-backed adapter share
        # this shape, and both are fault boundaries
        return f"client.{f.attr}", "hop"
    if recv == "subprocess" and f.attr in _SUBPROCESS_FNS:
        return f"subprocess.{f.attr}", "subprocess"
    if f.attr == "communicate":
        return f"{leaf}.communicate", "subprocess"
    if f.attr == "wait":
        return f"{leaf}.wait", "wait"
    if f.attr == "get" and "queue" in leaf.lower():
        return f"{leaf}.get", "queue-get"
    return None


def _sites_in(body: Sequence[ast.stmt]) -> List[ast.Call]:
    """Blocking calls in a statement list, NOT descending into nested
    function bodies (a closure's calls belong to its own scope)."""
    return [n for n in _own_body_walk_stmts(body)
            if isinstance(n, ast.Call) and classify_call(n) is not None]


def module_sites(mod: L.ModuleInfo) -> List[BlockingSite]:
    parents = _parents(mod.tree)
    out: List[BlockingSite] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        got = classify_call(node)
        if got is None:
            continue
        key, cls = got
        has_t, t_node = _timeout_arg(node, cls)
        out.append(BlockingSite(
            line=node.lineno, scope=_scope_of(node, parents, mod),
            key=key, cls=cls, has_timeout=has_t, timeout_node=t_node,
            node=node))
    return sorted(out, key=lambda s: s.line)


# -- helpers for the flow rules -----------------------------------------------


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _own_body_walk(fn: ast.AST):
    """ast.walk over a function body, skipping nested function bodies."""
    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    return _own_body_walk_stmts(body)


def _deadline_taint(fn: ast.AST, param: str) -> Set[str]:
    """Names derived (transitively, via simple assignments in the
    function's own body) from the deadline parameter — what a timeout
    expression must reference to count as budget-derived."""
    taint = {param}
    for _ in range(4):                       # small fixed point
        grew = False
        for n in _own_body_walk(fn):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = n.value
                if value is None or not (_names_in(value) & taint):
                    continue
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name) \
                                and nm.id not in taint:
                            taint.add(nm.id)
                            grew = True
        if not grew:
            break
    return taint


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler body only passes or only logs — no
    re-raise, no return, no state change a caller could observe."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            if isinstance(f, ast.Attribute):
                base = _dotted(f.value)
                if base is not None and (
                        base.rpartition(".")[2] in _LOG_RECEIVERS
                        or base in _LOG_RECEIVERS):
                    continue
            if isinstance(f, ast.Name) and f.id == "print":
                continue
        return False
    return True


def _handler_retries(handler: ast.ExceptHandler) -> bool:
    """True when the handler stays in the loop for another attempt: it
    neither re-raises nor exits the loop (break/return) — the shape
    that makes the enclosing loop a RETRY loop."""
    for n in ast.walk(handler):
        if isinstance(n, (ast.Raise, ast.Break, ast.Return)):
            return False
    return True


def _is_sleepish(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and "sleep" in f.attr:
        return True
    return isinstance(f, ast.Name) and "sleep" in f.id


def _loop_is_bounded(loop: ast.AST) -> bool:
    """A for-over-range (or any for) caps attempts; a while loop counts
    as bounded only when its test is a real condition (not ``True``)."""
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        return True
    test = loop.test
    return not (isinstance(test, ast.Constant) and bool(test.value))


# -- the pass -----------------------------------------------------------------


def run_faults(root: str, paths: Optional[List[str]] = None,
               ) -> Tuple[List[Finding], dict]:
    """The whole static pass over the production surface ->
    (findings, summary). ``summary`` carries ``fault_checks`` (real
    analysis units: sites classified, declarations validated, retry
    loops walked, deadline taints resolved, handlers examined — a
    vacuity guard on the count proves the rules saw the tree),
    ``fault_policies`` (per-module count of declared entries matching a
    live site) and ``vacuous`` (modules with blocking sites whose
    declaration matches none of them — the strict driver fails these)."""
    mods: List[L.ModuleInfo] = []
    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        if mod.relpath in _EXEMPT_RELPATHS:
            continue
        if any(mod.relpath.startswith(p) for p in _EXEMPT_PREFIXES):
            continue
        mods.append(mod)

    findings: List[Finding] = []
    checks = 0
    policies: Dict[str, int] = {}
    vacuous: List[str] = []

    for mod in mods:
        sites = module_sites(mod)
        policy, decl_line, malformed = declared_policy(mod)
        checks += len(sites) + (1 if policy is not None else 0)

        for msg in malformed:
            findings.append(Finding(
                "bare-blocking-call", mod.relpath, decl_line or 1,
                "<module>", f"malformed FAULT_POLICY: {msg}"))

        # -- bare-blocking-call: declaration coverage + timeouts --
        if sites and policy is None:
            findings.append(Finding(
                "bare-blocking-call", mod.relpath, sites[0].line,
                sites[0].scope,
                f"boundary module has {len(sites)} blocking site(s) "
                f"(first: {sites[0].key!r}) but declares no "
                "FAULT_POLICY — declare {site: (deadline_source, "
                "retry_class, degradation)} per site so the fault "
                "contract is reviewable"))
        matched: Set[str] = set()
        for s in sites:
            decl = (policy or {}).get(s.key)
            if decl is None:
                if policy is not None:
                    findings.append(Finding(
                        "bare-blocking-call", mod.relpath, s.line,
                        s.scope,
                        f"blocking site {s.key!r} ({s.cls}) has no "
                        "FAULT_POLICY entry — what bounds this wait "
                        "and what degrades when it fails?"))
                continue
            matched.add(s.key)
            if decl[0] not in _TIMEOUTLESS_OK and not s.has_timeout:
                findings.append(Finding(
                    "bare-blocking-call", mod.relpath, s.line, s.scope,
                    f"blocking site {s.key!r} is declared "
                    f"deadline_source={decl[0]!r} but the call passes "
                    "no timeout argument — the declared budget never "
                    "reaches the wait"))
        for key in sorted(set(policy or {}) - matched):
            findings.append(Finding(
                "bare-blocking-call", mod.relpath, decl_line or 1,
                "<module>",
                f"FAULT_POLICY declares site {key!r} but no such "
                "blocking call exists in this module (stale "
                "declaration)"))
        if policy is not None or sites:
            policies[mod.relpath] = len(matched)
            if sites and not matched:
                vacuous.append(mod.relpath)

        # -- per-function flow rules --
        for qual, fn in sorted(mod.functions.items()):
            if isinstance(fn, ast.Lambda):
                continue
            # unbounded-retry: loops retrying a blocking call through a
            # non-re-raising handler
            for node in _own_body_walk(fn):
                if not isinstance(node, (ast.For, ast.AsyncFor,
                                         ast.While)):
                    continue
                retrying = False
                for t in _own_body_walk_stmts(node.body):
                    if not isinstance(t, ast.Try):
                        continue
                    if not _sites_in(t.body):
                        continue
                    if any(_handler_retries(h) for h in t.handlers):
                        retrying = True
                        break
                if not retrying:
                    continue
                checks += 1
                if not _loop_is_bounded(node):
                    findings.append(Finding(
                        "unbounded-retry", mod.relpath, node.lineno,
                        qual,
                        "retry loop around a blocking call has no "
                        "attempt cap (while True) — a dead dependency "
                        "is retried forever (cap attempts and back "
                        "off, e.g. graftfault.HopPolicy)"))
                elif not any(isinstance(n, ast.Call) and _is_sleepish(n)
                             for n in _own_body_walk_stmts(node.body)):
                    findings.append(Finding(
                        "unbounded-retry", mod.relpath, node.lineno,
                        qual,
                        "retry loop around a blocking call has no "
                        "backoff sleep — a failing dependency is "
                        "hammered at full rate between attempts"))

            # deadline-drop
            args = getattr(fn, "args", None)
            if args is None:
                continue
            all_args = (args.posonlyargs + args.args + args.kwonlyargs)
            dl = next((a.arg for a in all_args
                       if a.arg in _DEADLINE_PARAMS), None)
            if dl is None:
                continue
            taint = _deadline_taint(fn, dl)
            checks += 1
            for node in _own_body_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                got = classify_call(node)
                if got is None:
                    continue
                key, cls = got
                has_t, t_node = _timeout_arg(node, cls)
                if has_t and t_node is not None \
                        and (_names_in(t_node) & taint):
                    continue
                findings.append(Finding(
                    "deadline-drop", mod.relpath, node.lineno, qual,
                    f"{qual} accepts a deadline ({dl!r}) but blocking "
                    f"site {key!r} does not derive its timeout from "
                    "the remaining budget — the deadline dies at this "
                    "hop (derive timeout via e.g. "
                    "deadline.timeout(cap))"))

        # -- swallowed-fault --
        parents = _parents(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            if not _sites_in(node.body):
                continue
            checks += 1
            for h in node.handlers:
                if _handler_swallows(h):
                    findings.append(Finding(
                        "swallowed-fault", mod.relpath, h.lineno,
                        _scope_of(h, parents, mod),
                        "except handler around a declared fault "
                        "boundary only passes/logs — the failure "
                        "vanishes with no retry, no typed error, no "
                        "degradation (surface it or route it through "
                        "the hop policy)"))

    summary = {
        "fault_checks": checks,
        "fault_policies": policies,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)


def _own_body_walk_stmts(body: Sequence[ast.stmt]):
    """Like :func:`_own_body_walk` but over a raw statement list."""
    stack: List[ast.AST] = list(body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
