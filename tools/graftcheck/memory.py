"""graftcheck memory pass: declared-HBM-ledger static analysis
(compile-free).

The graftmem ledger (``llm_sharding_demo_tpu/utils/graftmem.py``) only
earns the name "byte attribution" if every long-lived device holding
actually registers and nothing accumulates device arrays off-ledger —
a ledger with silent gaps is worse than none, because /debug/memory
LOOKS complete. This pass (the static half of graftmem, riding
``python -m tools.graftcheck`` and the strict in-suite driver — the
timeline pass's declaration/emission-scan split, applied to bytes)
holds the declarations to that bar:

In-file declarations (the registration-annotation idiom of
``TIMELINE_EVENTS`` / ``FAULT_POLICY`` / ``SLO_POLICY``):

- ``MEMORY_LEDGER``: ``{holding: component}`` — which long-lived device
  holdings this module owns and which graftmem component each
  attributes to (components are the fixed
  ``graftmem.MEMORY_COMPONENTS`` vocabulary, injectable here for
  fixtures).
- ``MEMORY_BOUNDS`` (optional): ``{container: bound}`` — containers
  that accumulate device arrays, with reviewable prose naming the
  bound (capacity + eviction policy). An undeclared accumulation site
  is the leak shape this pass exists to catch.

Rules (ids in brackets; suppressions ride the shared baseline):

- [untracked-device-state]    a persistent device-array attribute
                              (``self.X = jnp.zeros(...)`` /
                              ``jax.device_put`` / tree-map placement)
                              in a runtime/ module whose name is not in
                              MEMORY_LEDGER — the mirror of
                              undeclared-jit: residency landed off the
                              declared contract.
- [ledger-drift]              a malformed declaration (non-literal
                              dict, non-string entries); a declared
                              component outside the fixed vocabulary; a
                              declared holding with no
                              ``graftmem.track(owner, "<holding>", ...)``
                              site (stale — the module stopped
                              registering and the ledger silently lost
                              a component); a track site whose holding/
                              component is not a string literal, is
                              undeclared, or disagrees with the
                              declaration.
- [unbounded-device-growth]   a container accumulation site
                              (``self.X[k] = ...`` / ``self.X.append``)
                              in a runtime/ module whose stored value
                              builds device arrays (contains a jnp/jax
                              call) with no MEMORY_BOUNDS entry for
                              ``X`` — device bytes growing without a
                              declared bound.

``--strict`` additionally fails a VACUOUS pass (a module declaring
MEMORY_LEDGER none of whose holdings are tracked — the ledger went
dark); ``cli.run --json`` carries ``memory_checks`` /
``memory_ledgers`` / ``memory_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign

MEMORY_RULE_IDS = ("untracked-device-state", "ledger-drift",
                   "unbounded-device-growth")

# the attribute-assignment rule (and the container rule) apply to the
# modules that own serving-path device residency; ops/ kernels build
# transient values inside jit and utils/ holds no model state
_RUNTIME_PREFIX = "llm_sharding_demo_tpu/runtime/"

# the ledger itself is the apparatus, not a registrant (the
# grafttime/graftsched exemption precedent)
_EXEMPT_RELPATHS = ("llm_sharding_demo_tpu/utils/graftmem.py",)

# dotted call roots that MINT persistent device residency when assigned
# to an attribute: array constructors and explicit placement/deep-copy.
# jax.jit / movers / plain helper calls are not allocators.
_ALLOCATOR_CALLS = {
    ("jnp", "zeros"), ("jnp", "ones"), ("jnp", "full"),
    ("jnp", "empty"), ("jnp", "zeros_like"), ("jnp", "ones_like"),
    ("jnp", "full_like"), ("jnp", "asarray"), ("jnp", "array"),
    ("jax", "device_put"),
    ("jax", "tree", "map"), ("jax", "tree_util", "tree_map"),
}


def _dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """``jax.tree.map`` -> ("jax", "tree", "map"); None when the func
    is not a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _contains_allocator(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d in _ALLOCATOR_CALLS:
                return True
    return False


def _contains_device_call(node: ast.AST) -> bool:
    """Any jnp./jax.-rooted call — the container rule's broader net
    (``jax.tree.map(jnp.copy, cache)`` deep-copies device buffers into
    the store without being a constructor)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d and d[0] in ("jnp", "jax"):
                return True
    return False


class _TrackSite:
    __slots__ = ("line", "scope", "holding", "component", "literal")

    def __init__(self, line, scope, holding, component, literal):
        self.line = line
        self.scope = scope
        self.holding = holding      # str or None (non-literal)
        self.component = component  # str or None (non-literal)
        self.literal = literal


class _MemScanner(ast.NodeVisitor):
    """Collect, with enclosing scope: ``graftmem.track/update/release``
    call sites, persistent allocator attribute assignments, and
    container accumulation sites."""

    def __init__(self):
        self.tracks: List[_TrackSite] = []
        self.calls = 0  # update/release sites (checked as live usage)
        # attr name -> (line, scope) for self.X = <allocator expr>
        self.attr_allocs: List[Tuple[str, int, str]] = []
        # container name -> (line, scope) for device-array accumulation
        self.container_stores: List[Tuple[str, int, str]] = []
        self._scope = ["<module>"]

    def _visit_func(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _self_attr(self, node) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def visit_Assign(self, node):
        for tgt in node.targets:
            attr = self._self_attr(tgt)
            if attr is not None and _contains_allocator(node.value):
                self.attr_allocs.append((attr, node.lineno,
                                         self._scope[-1]))
            # self.X[k] = <device expr>
            if isinstance(tgt, ast.Subscript):
                attr = self._self_attr(tgt.value)
                if attr is not None \
                        and _contains_device_call(node.value):
                    self.container_stores.append((attr, node.lineno,
                                                  self._scope[-1]))
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        d = _dotted(f)
        if d and d[0] == "graftmem" and len(d) == 2:
            if d[1] == "track":
                holding = component = None
                literal = True
                for i, name in ((1, "holding"), (2, "component")):
                    val = None
                    if len(node.args) > i and isinstance(
                            node.args[i], ast.Constant) \
                            and isinstance(node.args[i].value, str):
                        val = node.args[i].value
                    else:
                        for kw in node.keywords:
                            if kw.arg == name and isinstance(
                                    kw.value, ast.Constant) \
                                    and isinstance(kw.value.value, str):
                                val = kw.value.value
                    if val is None:
                        literal = False
                    elif name == "holding":
                        holding = val
                    else:
                        component = val
                self.tracks.append(_TrackSite(node.lineno,
                                              self._scope[-1], holding,
                                              component, literal))
            elif d[1] in ("update", "release", "holding_bytes"):
                self.calls += 1
        # self.X.append(<device expr>)
        if isinstance(f, ast.Attribute) and f.attr == "append":
            attr = self._self_attr(f.value)
            if attr is not None and node.args \
                    and _contains_device_call(node.args[0]):
                self.container_stores.append((attr, node.lineno,
                                              self._scope[-1]))
        self.generic_visit(node)


def _declared_dict(stmt: ast.Assign
                   ) -> Optional[List[Tuple[str, str, int]]]:
    """MEMORY_LEDGER / MEMORY_BOUNDS dict literal ->
    [(key, value, line)]; None when not a statically readable
    string->string dict."""
    node = stmt.value
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            return None
        out.append((k.value, v.value, k.lineno))
    return out


def run_memory(root: str, paths: Optional[List[str]] = None,
               components: Optional[Dict[str, str]] = None,
               ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``memory_checks`` (declarations + track sites + allocator/
    container sites examined — the vacuity guard on the pass itself),
    ``memory_ledgers`` (per-module count of declared holdings with a
    live track site) and ``vacuous`` (modules whose MEMORY_LEDGER
    matches no registration — the strict driver fails these).
    ``components`` is injectable for rule fixtures; by default the real
    ``graftmem.MEMORY_COMPONENTS``."""
    if components is None:
        from llm_sharding_demo_tpu.utils import graftmem as GM
        components = GM.MEMORY_COMPONENTS

    findings: List[Finding] = []
    checks = 0
    ledgers_live: Dict[str, int] = {}
    vacuous: List[str] = []

    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        if mod.relpath in _EXEMPT_RELPATHS:
            continue
        in_runtime = mod.relpath.startswith(_RUNTIME_PREFIX)
        decl_stmt = _module_assign(mod, "MEMORY_LEDGER")
        bounds_stmt = _module_assign(mod, "MEMORY_BOUNDS")
        scanner = _MemScanner()
        scanner.visit(mod.tree)
        relevant = (decl_stmt is not None or bounds_stmt is not None
                    or scanner.tracks or scanner.calls
                    or (in_runtime and (scanner.attr_allocs
                                        or scanner.container_stores)))
        if not relevant:
            continue
        checks += 1

        declared: Dict[str, str] = {}
        declared_lines: Dict[str, int] = {}
        if decl_stmt is not None:
            entries = _declared_dict(decl_stmt)
            if entries is None:
                findings.append(Finding(
                    "ledger-drift", mod.relpath, decl_stmt.lineno,
                    "<module>",
                    "MEMORY_LEDGER must be a dict literal of string "
                    "holding -> string component (the memory pass "
                    "reads it statically)"))
            else:
                for holding, component, line in entries:
                    declared[holding] = component
                    declared_lines[holding] = line
                    checks += 1
                    if component not in components:
                        findings.append(Finding(
                            "ledger-drift", mod.relpath, line,
                            "<module>",
                            f"MEMORY_LEDGER maps {holding!r} to "
                            f"component {component!r}, outside the "
                            f"graftmem vocabulary "
                            f"({sorted(components)}) — a new residency "
                            "class is a reviewed "
                            "graftmem.MEMORY_COMPONENTS change"))

        bounds: Dict[str, str] = {}
        if bounds_stmt is not None:
            entries = _declared_dict(bounds_stmt)
            if entries is None:
                findings.append(Finding(
                    "unbounded-device-growth", mod.relpath,
                    bounds_stmt.lineno, "<module>",
                    "MEMORY_BOUNDS must be a dict literal of string "
                    "container -> string bound prose"))
            else:
                bounds = {k: v for k, v, _ in entries}

        # -- registration sites vs the declaration ------------------------
        tracked_holdings = set()
        for s in scanner.tracks:
            checks += 1
            if not s.literal:
                findings.append(Finding(
                    "ledger-drift", mod.relpath, s.line, s.scope,
                    "graftmem.track holding/component must be string "
                    "literals (a computed attribution is unreviewable "
                    "and unjoinable against MEMORY_LEDGER)"))
                continue
            tracked_holdings.add(s.holding)
            if s.component not in components:
                findings.append(Finding(
                    "ledger-drift", mod.relpath, s.line, s.scope,
                    f"graftmem.track component {s.component!r} is "
                    f"outside the vocabulary ({sorted(components)})"))
            if s.holding not in declared:
                findings.append(Finding(
                    "ledger-drift", mod.relpath, s.line, s.scope,
                    f"graftmem.track registers holding {s.holding!r} "
                    "not declared in this module's MEMORY_LEDGER"))
            elif declared[s.holding] != s.component:
                findings.append(Finding(
                    "ledger-drift", mod.relpath, s.line, s.scope,
                    f"graftmem.track attributes {s.holding!r} to "
                    f"{s.component!r} but MEMORY_LEDGER declares "
                    f"{declared[s.holding]!r} — the declaration and "
                    "the registration drifted"))
        checks += scanner.calls

        live = 0
        for holding, component in declared.items():
            if holding in tracked_holdings:
                live += 1
            else:
                findings.append(Finding(
                    "ledger-drift", mod.relpath,
                    declared_lines[holding], "<module>",
                    f"MEMORY_LEDGER declares {holding!r} but no "
                    "graftmem.track site in this module registers it — "
                    "the ledger silently lost a declared holding "
                    "(stale declaration?)"))
        if declared:
            ledgers_live[mod.relpath] = live
            if live == 0:
                vacuous.append(mod.relpath)

        # -- residency landing off the declared contract -------------------
        if in_runtime:
            for attr, line, scope in scanner.attr_allocs:
                checks += 1
                if attr not in declared:
                    findings.append(Finding(
                        "untracked-device-state", mod.relpath, line,
                        scope,
                        f"persistent device-array attribute "
                        f"``self.{attr}`` is allocated here but not "
                        "declared in MEMORY_LEDGER — residency the "
                        "graftmem ledger cannot attribute (the mirror "
                        "of undeclared-jit)"))
            for attr, line, scope in scanner.container_stores:
                checks += 1
                if attr not in bounds:
                    findings.append(Finding(
                        "unbounded-device-growth", mod.relpath, line,
                        scope,
                        f"container ``self.{attr}`` accumulates device "
                        "arrays here with no MEMORY_BOUNDS entry — "
                        "declare {container: bound} naming the "
                        "capacity and eviction policy"))

    summary = {
        "memory_checks": checks,
        "memory_ledgers": ledgers_live,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
