"""graftscope's static + dynamic graftcheck halves.

**Static pass** (``run_scope_static``, rides ``python -m
tools.graftcheck`` and the strict in-suite driver): profiling is a
DECLARED contract — every runtime module that declares
``JIT_ENTRY_POINTS`` also declares ``PROFILED_SCOPES`` (the entry
points whose dispatch sites are wrapped in
``graftscope.instrument(jax.jit(...), "mod._entry", key_fn=...)``), and
the ``unprofiled-entry-point`` rule (the mirror of ``undeclared-jit``)
verifies the declaration three ways:

- an entry point neither profiled nor baselined is a finding (a
  compiled-program population whose device time the attribution layer
  silently misses);
- a PROFILED_SCOPES name whose jit site is NOT actually wrapped in the
  instrument timer is a finding (a declared-but-dead contract);
- a PROFILED_SCOPES name that is not a JIT_ENTRY_POINT is a stale
  declaration.

Intentional cold-path exemptions (e.g. the GRAFTSAN-only ``_poison``
mover) are baselined in tools/graftcheck/baseline.txt with a
justification, keyed ``unprofiled-entry-point path::<entry name>``.
``--strict`` additionally fails a VACUOUS contract: a module with entry
points but zero instrument-wrapped sites means the attribution layer
stopped seeing that module entirely.

**Attribution mode** (``run_attribution``, ``python -m tools.graftcheck
scope``): the measured-vs-modeled join. Tiny real engines replay the
canonical workloads on this host with graftscope sync mode armed
(device-true dispatch windows), and each workload's observed dispatch
rings are joined against

- the recompile certifier's program-key sets (``recompile.engine_call_
  keys`` / ``paged_runner_keys``) — exact-marked workloads must join
  1:1: every certified key observed, nothing extra (a drifted key model
  means the budget certifies programs the runtime never mints, or
  misses ones it does);
- the cost model's per-token byte prediction (``costmodel.
  score_candidate``) — reported as measured seconds/token against
  modeled bytes/token, i.e. the implied HBM bandwidth this host
  sustained. The ratio is attribution, not a gate (hosts differ);
  regression GATING is tools/bench_diff.py's job, over the bench
  trajectory.

bench.py journals the attribution payload as the
``graftscope_attribution`` row beside ``graftcheck_static_analysis``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from . import lint as L
from .core import Finding

# entry-point name -> graftscope scope string (the instrument() label
# convention: "<module leaf>.<entry>") for the modules the certifier
# models — the join key between rings and certified populations
SCOPE_OF: Dict[str, str] = {
    "_prefill": "engine._prefill",
    "_prefill_chunked": "engine._prefill_chunked",
    "_decode_seg": "engine._decode_seg",
    "_loop": "spec_decode._loop",
    "_loop_b": "spec_decode._loop_b",
    "_seg_b": "spec_decode._seg_b",
    "_gather": "kv_pool._gather",
    "_scatter": "kv_pool._scatter",
    "_scatter_row": "kv_pool._scatter_row",
    "_copy": "kv_pool._copy",
    "_gather_q": "kv_pool._gather_q",
    "_scatter_q": "kv_pool._scatter_q",
    "_scatter_row_q": "kv_pool._scatter_row_q",
    "_copy_q": "kv_pool._copy_q",
}


# -- static pass --------------------------------------------------------------


def run_scope_static(root: str,
                     paths: Optional[List[str]] = None,
                     ) -> Tuple[List[Finding], dict]:
    """The unprofiled-entry-point rule over the production surface ->
    (findings, summary). Summary carries ``scope_checks`` (entry-point
    checks performed — the vacuity guard on the pass itself),
    ``profiled_regions`` (instrument-wrapped jit sites per module), and
    ``vacuous`` (modules with entry points but ZERO wrapped sites — the
    --strict failure class)."""
    findings: List[Finding] = []
    checks = 0
    profiled_regions: Dict[str, int] = {}
    vacuous: List[str] = []
    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        in_runtime = "/runtime/" in "/" + mod.relpath
        if not (mod.declared_entry_points or mod.declared_profiled):
            continue
        wrapped = {s.name for s in mod.jit_sites
                   if s.profiled and s.name is not None}
        decl_line = mod.profiled_decl_line or mod.entry_decl_line or 1
        for name in sorted(mod.declared_entry_points):
            checks += 1
            if name not in mod.declared_profiled:
                findings.append(Finding(
                    "unprofiled-entry-point", mod.relpath,
                    mod.entry_decl_line or 1, name,
                    f"jit entry point {name!r} is not in this module's "
                    "PROFILED_SCOPES — its dispatches are a compiled-"
                    "program population graftscope's device-time "
                    "attribution silently misses; wrap the jit site in "
                    "graftscope.instrument and declare it, or baseline "
                    "the exemption with a justification"))
            elif name not in wrapped:
                findings.append(Finding(
                    "unprofiled-entry-point", mod.relpath, decl_line,
                    name,
                    f"PROFILED_SCOPES declares {name!r} but its jit "
                    "site is not wrapped in a graftscope.instrument "
                    "dispatch timer — a declared-but-dead profiling "
                    "contract"))
        for name in sorted(mod.declared_profiled
                           - mod.declared_entry_points):
            checks += 1
            findings.append(Finding(
                "unprofiled-entry-point", mod.relpath, decl_line,
                name,
                f"PROFILED_SCOPES declares {name!r} but it is not a "
                "declared JIT_ENTRY_POINT (stale declaration)"))
        if mod.declared_entry_points:
            live = len(wrapped & mod.declared_entry_points)
            profiled_regions[mod.relpath] = live
            # the --strict vacuity class is RUNTIME modules (serving
            # dispatch surfaces) gone entirely unprofiled; a non-runtime
            # module whose only entry points are baselined test oracles
            # (ops/paged_attention) is the per-entry baseline's business
            if live == 0 and in_runtime:
                vacuous.append(mod.relpath)
    return findings, {"scope_checks": checks,
                      "profiled_regions": profiled_regions,
                      "vacuous": sorted(vacuous)}


# -- attribution mode ---------------------------------------------------------


def attribution_workloads():
    """(label, engine kwargs, paged kwargs or None, GenerateCalls) —
    the canonical shapes the join replays on real tiny engines. All
    rows are exact-marked (admission-mode / solo-paged), so the 1:1
    join is the acceptance bar for every one of them."""
    from . import recompile as R
    greedy = R.greedy_sampling()
    return [
        ("solo-greedy", dict(max_seq=64), None,
         [R.GenerateCall(prompt_lens=(8,), max_new=12, sampling=greedy)]),
        ("batch2-greedy", dict(max_seq=64), None,
         [R.GenerateCall(prompt_lens=(8, 8), max_new=12,
                         sampling=greedy)]),
        ("paged-solo", dict(max_seq=64),
         dict(num_blocks=16, block_size=8),
         [R.GenerateCall(prompt_lens=(8,), max_new=12, sampling=greedy)]),
    ]


def run_attribution() -> dict:
    """Replay the canonical workloads on real tiny engines with
    graftscope sync armed, join rings against certified program keys,
    and report measured-vs-modeled drift. CPU-safe (the bench chip is
    not required); see the module docstring for what gates and what
    merely reports."""
    import jax
    import numpy as np

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import (KVBlockPool,
                                                       PagedKVRunner)
    from llm_sharding_demo_tpu.utils import graftscope

    from . import costmodel as C, recompile as R

    cfg = gpt2.GPT2Config(vocab_size=96, n_positions=64, n_embd=16,
                          n_layer=2, n_head=2)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))

    saved = graftscope.dump_state()
    was_enabled = graftscope.set_enabled(True)
    was_sync = graftscope.set_sync(True)
    rows: List[dict] = []
    try:
        for label, eng_kw, paged_kw, calls in attribution_workloads():
            graftscope.clear()
            engine = DecodeEngine(params, cfg, **eng_kw)
            desc = R.EngineDesc(**eng_kw)
            runner = engine
            if paged_kw is not None:
                pool = KVBlockPool.for_engine(engine, **paged_kw)
                runner = PagedKVRunner(engine, pool)

            certified: Dict[str, set] = {}
            for call in calls:
                if paged_kw is not None:
                    paged = R.PagedDesc(max_seq=eng_kw["max_seq"],
                                        block_size=paged_kw["block_size"])
                    keysets = R.paged_runner_keys(desc, paged, call)
                else:
                    keysets = R.engine_call_keys(desc, call)
                for name, ks in keysets.items():
                    certified.setdefault(name, set()).update(ks)

            decode_steps = 0
            for call in calls:
                b = len(call.prompt_lens)
                s = max(call.prompt_lens)
                ids = np.full((b, s), 3, dtype=np.int32)
                # replay with the CALL's own sampling — the certified
                # keysets derive from it, and a divergent harness
                # default would report join drift that is nobody's bug
                sampling = (call.sampling if call.sampling is not None
                            else R.greedy_sampling())
                runner.generate(ids, call.max_new, sampling=sampling)
                decode_steps += b * (call.max_new - 1)

            join: Dict[str, dict] = {}
            joined = True
            for name in sorted(certified):
                cert = certified[name]
                observed = graftscope.program_keys(SCOPE_OF[name])
                missing = sorted(repr(k) for k in cert - set(observed))
                extra = sorted(repr(k) for k in set(observed) - cert)
                if missing or extra:
                    joined = False
                join[name] = {
                    "scope": SCOPE_OF[name],
                    "certified_programs": len(cert),
                    "observed_programs": len(observed),
                    "matched": len(cert & set(observed)),
                    "missing": missing,
                    "extra": extra,
                    "calls": sum(c for c, _ in observed.values()),
                    "seconds_total": round(
                        sum(s for _, s in observed.values()), 6),
                }

            # measured decode seconds per token (device-true — sync
            # mode closes every dispatch window via block_until_ready)
            decode_secs = graftscope.scope_seconds("engine._decode_seg")
            if paged_kw is not None:
                # the paged runner's per-segment pool round-trip is part
                # of its decode cost — attribute it honestly
                decode_secs += (graftscope.scope_seconds("kv_pool._gather")
                                + graftscope.scope_seconds(
                                    "kv_pool._scatter"))
            measured_per_token = (decode_secs / decode_steps
                                  if decode_steps else None)

            # modeled cost (bytes/token) for the matching candidate row
            b = max(len(c.prompt_lens) for c in calls)
            cand = C.Candidate(
                topology="single",
                batch_mode="admission", max_batch=b,
                kv_pool_blocks=(paged_kw or {}).get("num_blocks", 0),
                kv_block_size=(paged_kw or {}).get("block_size", 16))
            traffic = tuple(
                C.TrafficRow(max(c.prompt_lens), c.max_new,
                             len(c.prompt_lens)) for c in calls)
            scored = C.score_candidate(gpt2, cfg, cand, {},
                                       eng_kw["max_seq"], traffic, None)
            row = {
                "workload": label,
                "programs_exact": True,
                "joined_1to1": joined,
                "entry_points": join,
                "decode_steps": decode_steps,
                "measured_decode_seconds_per_token":
                    None if measured_per_token is None
                    else round(measured_per_token, 8),
                "modeled_cost_bytes_per_token":
                    round(scored.cost_per_token, 1),
                "modeled_hbm_bytes_per_device":
                    scored.hbm_bytes_per_device,
                "modeled_comm_bytes_per_token":
                    scored.comm_bytes_per_token,
            }
            if measured_per_token:
                # the drift number: what byte rate this host would have
                # to sustain for the model's cost to equal the measured
                # time — compare ACROSS runs/trajectory, not to a spec
                # sheet (that is bench_diff's job)
                row["implied_bytes_per_second"] = round(
                    scored.cost_per_token / measured_per_token, 1)
            rows.append(row)
    finally:
        graftscope.set_enabled(was_enabled)
        graftscope.set_sync(was_sync)
        graftscope.restore_state(saved)

    return {
        "ok": all(r["joined_1to1"] for r in rows),
        "sync": True,
        "note": ("measured windows are device-true (GRAFTSCOPE sync); "
                 "join is gated (exact rows must match 1:1), bandwidth "
                 "drift is reported for the bench trajectory"),
        "workloads": rows,
    }


def main_scope(args) -> int:
    """``python -m tools.graftcheck scope`` body (cli.py dispatches)."""
    import json
    payload = run_attribution()
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0 if payload["ok"] else 1
    for row in payload["workloads"]:
        mark = "ok " if row["joined_1to1"] else "DRIFT"
        mpt = row["measured_decode_seconds_per_token"]
        print(f" {mark} {row['workload']:<16} "
              f"programs {sum(e['observed_programs'] for e in row['entry_points'].values())} "
              f"measured {mpt if mpt is not None else '-'} s/tok "
              f"modeled {row['modeled_cost_bytes_per_token']} B/tok "
              f"implied {row.get('implied_bytes_per_second', '-')} B/s")
        for name, e in sorted(row["entry_points"].items()):
            if e["missing"] or e["extra"]:
                print(f"      {name}: certified {e['certified_programs']}"
                      f" observed {e['observed_programs']}"
                      f" missing {e['missing']} extra {e['extra']}")
    print("graftcheck scope: "
          + ("measured rings join certified program keys 1:1"
             if payload["ok"] else
             "JOIN DRIFT — the certifier's key model and the runtime "
             "disagree (see rows above)"))
    return 0 if payload["ok"] else 1
