"""graftcheck slo pass: declared-SLO static analysis (compile-free).

The graftload harness (``llm_sharding_demo_tpu/loadgen/``) measures
goodput against DECLARED service-level objectives — and a declared
target is only worth gating on if the number it binds is actually
measured. This pass (the static half of graftload, riding ``python -m
tools.graftcheck`` and the strict in-suite driver, mirroring the
faults/locks/sanitize/scope split) holds the declarations to that bar:

In-file declarations (the registration-annotation idiom of
``FAULT_POLICY`` / ``GUARDED_STATE`` / ``PROFILED_SCOPES``):

- ``PROFILES``: dict literal keyed by profile name — the workload
  registry (``loadgen/profiles.py``);
- ``SLO_POLICY``: ``{profile: {metric: (target, percentile)}}`` over
  the fixed vocabulary ``ttft`` / ``tpot`` / ``e2e`` /
  ``deadline_miss`` — one entry per registered profile;
- ``SLO_SOURCE_METRICS``: ``{metric: catalog_name}`` — which
  ``METRIC_CATALOG`` series each vocabulary metric is computed from.

Rules (ids in brackets; suppressions ride the shared baseline):

- [profile-without-slo]        a registered profile with no SLO_POLICY
                               entry (or an empty one), a module
                               declaring PROFILES but no SLO_POLICY at
                               all, a STALE policy entry naming no
                               registered profile, or a malformed
                               declaration (non-literal dict, target
                               not a positive number — deadline_miss
                               may declare a zero rate cap —
                               percentile outside (0, 100]).
- [slo-without-source-metric]  a declared SLO metric outside the fixed
                               vocabulary, one with no
                               SLO_SOURCE_METRICS mapping, one whose
                               mapped series is missing from
                               METRIC_CATALOG, or one whose mapped
                               series is never emitted at any
                               request-path call site (REGISTRY.inc/
                               observe/gauge or timed()) — a target
                               nobody measures is a promise nobody can
                               keep OR break.

``--strict`` additionally fails a VACUOUS pass (a module declaring
SLO_POLICY with zero entries matching a live profile — the contract
stopped seeing the registry); ``cli.run --json`` carries
``slo_checks`` / ``slo_policies`` / ``slo_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign

SLO_RULE_IDS = ("profile-without-slo", "slo-without-source-metric")

# the fixed vocabulary (loadgen/profiles.py SLO_METRICS mirrors this —
# tests pin the two stay equal)
SLO_METRICS = ("ttft", "tpot", "e2e", "deadline_miss")


def _str_dict_keys(node: ast.AST) -> Optional[List[Tuple[str, ast.AST]]]:
    """Dict literal -> [(str key, value node)]; None when not that."""
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        out.append((k.value, v))
    return out


def _target_tuple(node: ast.AST) -> Optional[Tuple[float, float]]:
    """``(target, percentile)`` of numeric constants, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)) or len(node.elts) != 2:
        return None
    vals = []
    for e in node.elts:
        if not (isinstance(e, ast.Constant)
                and isinstance(e.value, (int, float))
                and not isinstance(e.value, bool)):
            return None
        vals.append(float(e.value))
    return vals[0], vals[1]


def _emitted_metric_names(root: str,
                          paths: Optional[List[str]] = None) -> Set[str]:
    """Metric names emitted at production call sites — the same
    REGISTRY.inc/observe/gauge + timed() + graftscope.sample surface
    the metric-catalog rule scans."""
    from . import metric_catalog as MC
    names: Set[str] = set()
    for path in (paths if paths is not None else MC._iter_sources(root)):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        for m in MC._CALL_RE.finditer(text):
            names.add(m.group(2))
        for m in MC._TIMED_RE.finditer(text):
            names.add(m.group(1))
        for m in MC._SAMPLE_RE.finditer(text):
            names.add(m.group(1))
    return names


def run_slo(root: str, paths: Optional[List[str]] = None,
            catalog: Optional[Dict[str, str]] = None,
            emitted: Optional[Set[str]] = None,
            ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``slo_checks`` (declarations validated + per-metric
    resolutions — the vacuity guard on the pass itself),
    ``slo_policies`` (per-module count of policy entries matching a
    registered profile) and ``vacuous`` (modules whose SLO_POLICY
    matches no profile — the strict driver fails these).
    ``catalog``/``emitted`` are injectable for rule fixtures; by
    default the real METRIC_CATALOG and the scanned production
    emission sites."""
    if catalog is None:
        from llm_sharding_demo_tpu.utils.metrics import METRIC_CATALOG
        catalog = METRIC_CATALOG
    if emitted is None:
        emitted = _emitted_metric_names(root, paths=paths)

    findings: List[Finding] = []
    checks = 0
    policies: Dict[str, int] = {}
    vacuous: List[str] = []

    for path in (paths if paths is not None else L.iter_sources(root)):
        mod = L.index_module(path, root)
        if mod is None:
            continue
        prof_stmt = _module_assign(mod, "PROFILES")
        slo_stmt = _module_assign(mod, "SLO_POLICY")
        src_stmt = _module_assign(mod, "SLO_SOURCE_METRICS")
        if prof_stmt is None and slo_stmt is None:
            continue
        checks += 1

        profile_names: Set[str] = set()
        if prof_stmt is not None:
            entries = _str_dict_keys(prof_stmt.value)
            if entries is None:
                findings.append(Finding(
                    "profile-without-slo", mod.relpath,
                    prof_stmt.lineno, "<module>",
                    "PROFILES must be a dict literal with string "
                    "profile-name keys (the slo pass reads them "
                    "statically)"))
            else:
                profile_names = {k for k, _ in entries}

        if prof_stmt is not None and slo_stmt is None:
            findings.append(Finding(
                "profile-without-slo", mod.relpath, prof_stmt.lineno,
                "<module>",
                f"module registers {len(profile_names)} workload "
                "profile(s) but declares no SLO_POLICY — declare "
                "{profile: {metric: (target, percentile)}} so every "
                "profile's service promise is reviewable"))
            continue

        sources: Dict[str, str] = {}
        if src_stmt is not None:
            entries = _str_dict_keys(src_stmt.value)
            if entries is not None:
                sources = {k: v.value for k, v in entries
                           if isinstance(v, ast.Constant)
                           and isinstance(v.value, str)}

        decl = _str_dict_keys(slo_stmt.value)
        line = slo_stmt.lineno
        if decl is None:
            findings.append(Finding(
                "profile-without-slo", mod.relpath, line, "<module>",
                "SLO_POLICY must be a dict literal keyed by profile "
                "name"))
            continue

        matched = 0
        declared_profiles = {k for k, _ in decl}
        for name in sorted(profile_names - declared_profiles):
            checks += 1
            findings.append(Finding(
                "profile-without-slo", mod.relpath,
                (prof_stmt.lineno if prof_stmt is not None else line),
                name,
                f"profile {name!r} is registered but declares no "
                "SLO_POLICY entry — what latency/goodput promise does "
                "this traffic shape serve under?"))
        for name, policy_node in decl:
            checks += 1
            if profile_names and name not in profile_names:
                findings.append(Finding(
                    "profile-without-slo", mod.relpath, line, name,
                    f"SLO_POLICY declares profile {name!r} but no such "
                    "profile is registered in PROFILES (stale "
                    "declaration)"))
                continue
            metrics = _str_dict_keys(policy_node)
            if not metrics:
                findings.append(Finding(
                    "profile-without-slo", mod.relpath, line, name,
                    f"profile {name!r}: SLO_POLICY entry must be a "
                    "non-empty dict literal {metric: (target, "
                    "percentile)} — an empty promise gates nothing"))
                continue
            matched += 1
            for metric, target_node in metrics:
                checks += 1
                if metric not in SLO_METRICS:
                    findings.append(Finding(
                        "slo-without-source-metric", mod.relpath, line,
                        name,
                        f"profile {name!r}: unknown SLO metric "
                        f"{metric!r} (vocabulary: {SLO_METRICS})"))
                    continue
                tgt = _target_tuple(target_node)
                # deadline_miss is a rate CAP, where zero tolerance
                # (0.0, 100) is the strictest valid promise; latency
                # targets must be positive durations
                floor_ok = tgt is not None and (
                    tgt[0] >= 0 if metric == "deadline_miss"
                    else tgt[0] > 0)
                if tgt is None or not floor_ok \
                        or not 0 < tgt[1] <= 100:
                    findings.append(Finding(
                        "profile-without-slo", mod.relpath, line, name,
                        f"profile {name!r}: metric {metric!r} must "
                        "declare a (positive target — >= 0 for the "
                        "deadline_miss rate cap — percentile in "
                        "(0, 100]) literal pair"))
                    continue
                source = sources.get(metric)
                if source is None:
                    findings.append(Finding(
                        "slo-without-source-metric", mod.relpath, line,
                        name,
                        f"profile {name!r}: metric {metric!r} has no "
                        "SLO_SOURCE_METRICS mapping — which "
                        "METRIC_CATALOG series is this target computed "
                        "from?"))
                    continue
                if source not in catalog:
                    findings.append(Finding(
                        "slo-without-source-metric", mod.relpath, line,
                        name,
                        f"profile {name!r}: metric {metric!r} maps to "
                        f"{source!r}, which is not in METRIC_CATALOG — "
                        "the declared target references a series that "
                        "does not exist"))
                    continue
                if source not in emitted:
                    findings.append(Finding(
                        "slo-without-source-metric", mod.relpath, line,
                        name,
                        f"profile {name!r}: metric {metric!r} maps to "
                        f"{source!r}, which no request-path call site "
                        "emits — a target nobody measures cannot be "
                        "attained or missed"))
        policies[mod.relpath] = matched
        if matched == 0:
            vacuous.append(mod.relpath)

    summary = {
        "slo_checks": checks,
        "slo_policies": policies,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
