"""graftcheck placement pass: declared placement contracts (compile-free).

The static half of **graftshard** (``llm_sharding_demo_tpu/utils/
graftshard.py`` is the dynamic half — the same static+dynamic split as
sanitize/locks/faults/slo/fleet/watch/timeline/memory/numerics). Every
sharded program in this repo places its tensors somewhere on the mesh;
until now WHERE was prose plus a handful of pspec-validity checks in
the semantic pass. Nothing verified that a declared placement is what
the lowered program actually establishes — exactly the hazard surface a
multi-axis KV-sharded pool (ROADMAP item 1, Helix-style per-tensor-class
axis choice) walks into. This pass makes placement a DECLARED contract:

One vocabulary, :data:`MESH_AXES` — every mesh axis any program in the
repo may name (``pp``/``tp``/``ep``/``dp``/``sp`` plus the new ``kvp``
KV-partition axis the planner enumerates). Every module whose programs
or long-lived buffers take a position on the mesh declares
``PLACEMENT_CONTRACT`` beside ``JIT_ENTRY_POINTS``::

    PLACEMENT_CONTRACT = {
        "mesh_axes": ("pp",),            # axes this module's programs
                                         # may establish placement over
        "holding:blocks": "pp",          # self.blocks sharded over pp
        "holding:shared": "replicated",  # explicitly replicated
        "entry:_pp_blocks": "pp",        # traced entry's placement axis
    }

``holding:<name>`` keys declare the placement class of a long-lived
buffer (a ``self.<name>`` attribute — the same names graftmem's
MEMORY_LEDGER tracks, which is how the dynamic auditor joins a live
``.sharding`` to its declaration); ``entry:<name>`` keys declare the
mesh axis a traced entry point's program establishes. Values are an
axis from the module's declared ``mesh_axes`` or the literal
``"replicated"``. ``models/`` modules declare through their existing
``SHARDING_DESCRIPTOR`` (validated here against the descriptor
vocabulary, now including ``kvp_divisors`` — the config fields a kvp
axis must divide).

Two analysis halves feed four rules:

- **AST half** (always on): contract shape/vocabulary validation, the
  holding/entry liveness checks, SHARDING_DESCRIPTOR vocabulary, the
  manual-collective trigger (a module CALLING ``lax.ppermute`` must
  declare a contract), string-literal collective axes against
  MESH_AXES, and the hot-path reshard scan over GRAFTCHECK_HOT_LOOPS
  scopes.
- **Jaxpr half** (skipped under ``--lint-only``): the semantic/numerics
  trace pattern — :func:`traced_placements` builds compile-free
  ``jax.make_jaxpr`` programs of the REAL entry points over
  ``AbstractMesh`` stand-ins and reads the placement they actually
  establish: shard_map in/out names, collective axis names, and
  sharding-constraint specs.

Rules (ids in brackets; suppressions ride the shared baseline):

- [placement-drift]        a malformed/stale PLACEMENT_CONTRACT or
                           SHARDING_DESCRIPTOR, a collective-issuing
                           module with no contract, or a traced entry
                           whose established placement disagrees with
                           its declaration (declares ``pp`` but the
                           program establishes none; declares
                           ``replicated`` but the program shards).
- [undeclared-collective]  a collective (psum/all_gather/ppermute/
                           all_to_all/...) over an axis outside
                           MESH_AXES, or outside the module's declared
                           ``mesh_axes`` — subsumes the axis half of
                           the ring-bijection check.
- [replicated-large-buffer] a shard_map operand above the byte
                           threshold entering fully replicated from a
                           module with no explicit ``"replicated"``
                           holding declaration — the accidental-pool-
                           replication trap a kvp-sharded pool must
                           fail loudly on.
- [hot-path-reshard]       a ``with_sharding_constraint`` / sharded
                           ``device_put`` inside a GRAFTCHECK_HOT_LOOPS
                           decode scope — an implicit per-token
                           resharding; baseline-suppressible with
                           justification like host-sync.

This module is also the single source of truth for PartitionSpec
validity (:func:`check_pspec` — axis-exists / rank-fits / axis-used-
once / divisibility), relocated from semantic.py; semantic keeps a thin
call-through so its fixtures stay pinned.

``--strict`` additionally fails a VACUOUS pass (a PLACEMENT_CONTRACT
resolving to zero live holdings/entries); ``cli.run --json`` carries
``placement_checks`` / ``placement_contracts`` / ``placement_vacuous``.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from . import lint as L
from .core import Finding
from .locks import _module_assign
from .numerics import _const, _str_seq

PLACEMENT_RULE_IDS = ("placement-drift", "undeclared-collective",
                      "replicated-large-buffer", "hot-path-reshard")

# THE mesh-axis vocabulary: every axis any program in the repo may
# establish placement over. ``kvp`` is the KV-partition axis (Helix-
# style: the paged pool's kv-head dim sharded independently of tp) the
# planner enumerates; graftshard.MESH_AXES mirrors this — tests pin the
# two stay equal, like graftnum.REGIMES.
MESH_AXES = ("pp", "tp", "ep", "kvp", "dp", "sp")

REPLICATED = "replicated"

# replicated-large-buffer threshold: a fully replicated shard_map
# operand at/above this many bytes needs an explicit "replicated"
# holding declaration (the stand-in traces run far below it; a real
# pool plane is far above)
DEFAULT_REPLICATED_THRESHOLD = 1 << 20

_SPMD_PATH = "llm_sharding_demo_tpu/parallel/spmd.py"

# the descriptor vocabulary models/ declare placement through (the
# planner's derive_pspecs/gate_candidate read the same keys)
DESCRIPTOR_KEYS = ("column", "row", "expert",
                   "tp_divisors", "ep_divisors", "kvp_divisors")


# -- PartitionSpec validity (single source of truth; semantic.py keeps
# -- a thin call-through so its fixtures stay pinned) -------------------------


def check_pspec(spec, shape: Tuple[int, ...], mesh_axes: Dict[str, int],
                where: str) -> List[Finding]:
    """One spec against one array shape and a mesh's {axis: size}."""
    problems: List[str] = []
    entries = list(spec)
    if len(entries) > len(shape):
        problems.append(
            f"spec rank {len(entries)} exceeds array rank {len(shape)} "
            f"for shape {shape}")
        entries = entries[:len(shape)]
    used: Dict[str, int] = {}
    for dim, entry in enumerate(entries):
        axes = entry if isinstance(entry, tuple) else (entry,)
        factor = 1      # a dim sharded over SEVERAL axes splits by their
        for axis in axes:  # PRODUCT — per-axis checks alone would pass
            if axis is None:  # specs the real mesh rejects
                continue
            if axis not in mesh_axes:
                problems.append(
                    f"dim {dim} names mesh axis {axis!r}, mesh has "
                    f"{sorted(mesh_axes)}")
                continue
            if axis in used:
                problems.append(
                    f"mesh axis {axis!r} used on dims {used[axis]} and "
                    f"{dim} — an axis shards at most one dim")
            used[axis] = dim
            factor *= mesh_axes[axis]
        if factor > 1 and shape[dim] % factor:
            axes_str = "*".join(repr(a) for a in axes if a is not None)
            problems.append(
                f"dim {dim} of size {shape[dim]} not divisible by "
                f"mesh axis {axes_str}={factor}")
    return [Finding("pspec", _SPMD_PATH, 1, where, p) for p in problems]


# -- contract model ----------------------------------------------------------


class _Contract:
    """One parsed PLACEMENT_CONTRACT."""

    def __init__(self, line: int):
        self.line = line
        self.mesh_axes: Tuple[str, ...] = ()
        self.holdings: Dict[str, str] = {}   # name -> axis | "replicated"
        self.entries: Dict[str, str] = {}    # name -> axis | "replicated"

    def has_replicated_holding(self) -> bool:
        return any(v == REPLICATED for v in self.holdings.values())


def _str_dict_items(node: ast.AST) -> Optional[List[Tuple[str, ast.AST]]]:
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        out.append((k.value, v))
    return out


def _parse_contract(mod: L.ModuleInfo,
                    findings: List[Finding]) -> Optional[_Contract]:
    """PLACEMENT_CONTRACT -> validated contract; malformed declarations
    land as placement-drift findings (the contract itself is the first
    thing held to the vocabulary). Returns None when the module
    declares nothing."""
    stmt = _module_assign(mod, "PLACEMENT_CONTRACT")
    if stmt is None:
        return None
    line = stmt.lineno
    c = _Contract(line)
    items = _str_dict_items(stmt.value)
    if items is None:
        findings.append(Finding(
            "placement-drift", mod.relpath, line, "<module>",
            "PLACEMENT_CONTRACT must be a dict literal keyed by "
            "'mesh_axes' / 'holding:<name>' / 'entry:<name>' (the "
            "placement pass reads it statically)"))
        return c
    fmap = dict(items)
    axes = _str_seq(fmap.get("mesh_axes", ast.Dict(keys=[], values=[])))
    if axes is None or not axes \
            or any(a not in MESH_AXES for a in axes):
        findings.append(Finding(
            "placement-drift", mod.relpath, line, "<module>",
            "PLACEMENT_CONTRACT must declare 'mesh_axes' as a non-empty "
            f"tuple/list literal of axes from {MESH_AXES} (the single "
            "placement vocabulary)"))
        return c
    c.mesh_axes = tuple(axes)
    ok_values = set(c.mesh_axes) | {REPLICATED}
    for key, vnode in items:
        if key == "mesh_axes":
            continue
        kind, sep, name = key.partition(":")
        if not sep or kind not in ("holding", "entry") or not name:
            findings.append(Finding(
                "placement-drift", mod.relpath, line, key,
                f"contract key {key!r} must be 'mesh_axes', "
                "'holding:<name>' or 'entry:<name>'"))
            continue
        value = _const(vnode)
        if value not in ok_values:
            findings.append(Finding(
                "placement-drift", mod.relpath, line, key,
                f"contract value for {key!r} is {value!r}; want "
                f"\"replicated\" or a declared mesh axis "
                f"{sorted(c.mesh_axes)}"))
            continue
        (c.holdings if kind == "holding" else c.entries)[name] = value
    return c


def _parse_descriptor(mod: L.ModuleInfo,
                      findings: List[Finding]) -> Optional[Dict[str, tuple]]:
    """models/ SHARDING_DESCRIPTOR -> {key: names}; malformed shapes
    are placement-drift findings (the planner's derive_pspecs and
    gate_candidate read the same literal)."""
    stmt = _module_assign(mod, "SHARDING_DESCRIPTOR")
    if stmt is None:
        return None
    line = stmt.lineno
    items = _str_dict_items(stmt.value)
    if items is None:
        findings.append(Finding(
            "placement-drift", mod.relpath, line, "<module>",
            "SHARDING_DESCRIPTOR must be a dict literal keyed by the "
            f"descriptor vocabulary {DESCRIPTOR_KEYS}"))
        return {}
    out: Dict[str, tuple] = {}
    for key, vnode in items:
        if key not in DESCRIPTOR_KEYS:
            findings.append(Finding(
                "placement-drift", mod.relpath, line, key,
                f"SHARDING_DESCRIPTOR key {key!r} is outside the "
                f"descriptor vocabulary {DESCRIPTOR_KEYS}"))
            continue
        names = _str_seq(vnode)
        if names is None:
            findings.append(Finding(
                "placement-drift", mod.relpath, line, key,
                f"SHARDING_DESCRIPTOR[{key!r}] must be a tuple/list "
                "literal of field-name strings"))
            continue
        out[key] = tuple(names)
    return out


def _holding_sites(mod: L.ModuleInfo) -> Dict[str, int]:
    """name -> first line of a ``self.<name> = ...`` assignment — the
    attributes a 'holding:' declaration can be live against (the same
    names graftmem's track() registers)."""
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out.setdefault(t.attr, t.lineno)
    return out


def _resolve_entry_fn(mod: L.ModuleInfo, name: str) -> Optional[ast.AST]:
    fn = mod.functions.get(name)
    if fn is not None:
        return fn
    hit = L._suffix_index(mod).get(name)
    return hit[1] if hit is not None else None


# -- AST half ----------------------------------------------------------------


_COLLECTIVE_CALL_NAMES = ("ppermute", "psum", "all_gather", "all_to_all",
                          "reduce_scatter", "pmax", "pmin")


def _collective_calls(mod: L.ModuleInfo) -> List[Tuple[int, str,
                                                       Optional[str]]]:
    """(line, primitive, axis-or-None) per ``lax.<collective>`` call in
    the module. The axis is resolved only when passed as a string
    literal (positionally arg 1 or via ``axis_name=``); a variable axis
    is None — checked by the traced half instead."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in _COLLECTIVE_CALL_NAMES):
            continue
        axis = None
        if len(node.args) > 1:
            axis = _const(node.args[1])
        if axis is None:
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis = _const(kw.value)
        out.append((node.lineno, f.attr,
                    axis if isinstance(axis, str) else None))
    return out


def _reshard_sites(fn: ast.AST) -> List[Tuple[int, str]]:
    """(line, spelling) per sharding transition in a hot-loop body:
    ``with_sharding_constraint`` always, ``device_put`` when it names a
    placement (second positional arg or device=/sharding= keyword)."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr == "with_sharding_constraint":
            out.append((node.lineno, "with_sharding_constraint"))
        elif f.attr == "device_put" and (
                len(node.args) > 1
                or any(kw.arg in ("device", "sharding")
                       for kw in node.keywords)):
            out.append((node.lineno, "device_put"))
    return out


def _module_issues_collectives(mod: L.ModuleInfo) -> Optional[int]:
    """First line of a manual ``ppermute`` CALL — the signature of a
    hand-written ring program, the trigger that a module must declare
    PLACEMENT_CONTRACT (docstring mentions don't count; ``psum`` alone
    doesn't either — GSPMD-era helpers psum outside any placement
    story of their own)."""
    for line, prim, _axis in _collective_calls(mod):
        if prim == "ppermute":
            return line
    return None


# -- jaxpr half --------------------------------------------------------------


class TracedPlacement:
    """One production entry point traced at representative avals.

    ``build`` is called lazily (imports jax + the target module) and
    returns ``(fn, args)`` for ``jax.make_jaxpr(fn)(*args)``. The
    (relpath, entry) pair joins the trace to its declared
    ``entry:<name>`` contract row."""

    def __init__(self, relpath: str, entry: str,
                 build: Callable[[], tuple]):
        self.relpath = relpath
        self.entry = entry
        self.build = build


def traced_placements() -> List[TracedPlacement]:
    """The production trace table: the real pipelined decode step
    (``PipelinedDecoder._pp_blocks`` — the same program the overlap
    lint walks and the cost model prices), the gpipe training pipeline
    program, and the ring-attention kernel, each over an
    ``AbstractMesh`` stand-in. Kept beside the rules so adding a traced
    entry and its contract is one review."""
    PPDECODE = "llm_sharding_demo_tpu/parallel/ppdecode.py"
    GPIPE = "llm_sharding_demo_tpu/parallel/gpipe.py"
    RING = "llm_sharding_demo_tpu/ops/ring_attention.py"

    def _ppdecode():
        from . import semantic
        rows = [r for r in semantic.build_ppdecode_programs(2)
                if r[0].endswith("decode-step")]
        (_label, _scope, fn, args), = rows
        return fn, args

    def _gpipe():
        import jax
        import jax.numpy as jnp
        from jax.sharding import AbstractMesh

        from llm_sharding_demo_tpu.parallel import gpipe
        from llm_sharding_demo_tpu.parallel import partition as Pt
        from . import registry
        module, config = registry.families()["gpt2-tiny"]
        mesh = AbstractMesh((("pp", 2),))
        specs = Pt.make_stage_specs(
            config.n_layer, Pt.balanced_boundaries(config.n_layer, 2))
        pavals = jax.eval_shape(
            lambda k: module.init_params(config, k), jax.random.PRNGKey(0))
        blocks = jax.eval_shape(
            lambda p: Pt.stack_stage_params(p, specs), pavals)
        fn = gpipe._compiled_pipeline(mesh, config, "pp", False, 2, False)
        h = jax.ShapeDtypeStruct((2, 1, 4, config.n_embd), jnp.float32)
        return fn, (blocks, h)

    def _ring():
        import jax
        import jax.numpy as jnp
        from jax.sharding import AbstractMesh

        from llm_sharding_demo_tpu.ops import ring_attention as RA
        mesh = AbstractMesh((("sp", 2),))
        q = jax.ShapeDtypeStruct((1, 2, 4, 4), jnp.float32)
        return (lambda q, k, v: RA.ring_attention(q, k, v, mesh),
                (q, q, q))

    return [
        TracedPlacement(PPDECODE, "_pp_blocks", _ppdecode),
        TracedPlacement(GPIPE, "_compiled_pipeline", _gpipe),
        TracedPlacement(RING, "ring_attention", _ring),
    ]


def _spec_axes(spec) -> Set[str]:
    """Axis names a PartitionSpec (or shard_map names dict) mentions."""
    axes: Set[str] = set()
    for entry in spec:
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            if isinstance(a, str):
                axes.add(a)
    return axes


def _names_axes(names) -> Set[str]:
    """shard_map ``in_names``/``out_names`` dict ({dim: (axes,)}) ->
    axis-name set."""
    axes: Set[str] = set()
    if isinstance(names, dict):
        for v in names.values():
            for a in (v if isinstance(v, (tuple, list)) else (v,)):
                if isinstance(a, str):
                    axes.add(a)
    return axes


def _walk_eqns(jaxpr):
    from .semantic import _sub_jaxprs
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub)


def analyze_program(closed) -> dict:
    """Read the placement a traced program actually establishes:

    - ``axes``: every mesh-axis name the program references (shard_map
      in/out names, collective axis params, sharding-constraint specs);
    - ``collectives``: deduped (primitive, axis) pairs;
    - ``replicated_in``: per shard_map eqn, the (shape, dtype, nbytes)
      of operands entering with NO axis names (fully replicated);
    - ``constraints``: sharding-constraint axis-name sets.
    """
    from .semantic import COMM_PRIMITIVES
    axes: Set[str] = set()
    collectives: Set[Tuple[str, str]] = set()
    replicated_in: List[Tuple[tuple, str, int]] = []
    constraints: List[Set[str]] = []
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    for eqn in _walk_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim == "shard_map":
            in_names = eqn.params.get("in_names",
                                      eqn.params.get("in_specs", ()))
            for var, names in zip(eqn.invars, in_names):
                got = (_names_axes(names) if isinstance(names, dict)
                       else _spec_axes(names))
                axes |= got
                aval = getattr(var, "aval", None)
                if not got and aval is not None \
                        and hasattr(aval, "shape"):
                    import numpy as np
                    nbytes = (int(np.prod(aval.shape, dtype=np.int64))
                              * np.dtype(aval.dtype).itemsize)
                    replicated_in.append((tuple(aval.shape),
                                          str(aval.dtype), nbytes))
            for names in eqn.params.get("out_names",
                                        eqn.params.get("out_specs", ())):
                axes |= (_names_axes(names) if isinstance(names, dict)
                         else _spec_axes(names))
        elif prim in COMM_PRIMITIVES:
            names = eqn.params.get("axis_name",
                                   eqn.params.get("axes", ()))
            if not isinstance(names, (tuple, list)):
                names = (names,)
            for a in names:
                if isinstance(a, str):
                    axes.add(a)
                    collectives.add((prim, a))
        elif prim == "sharding_constraint":
            spec = getattr(eqn.params.get("sharding"), "spec", None)
            if spec is not None:
                got = _spec_axes(spec)
                axes |= got
                constraints.append(got)
    return {"axes": axes, "collectives": collectives,
            "replicated_in": replicated_in, "constraints": constraints}


def _check_traced(entry: TracedPlacement, contract: _Contract,
                  want: str, line: int, threshold: int,
                  findings: List[Finding]) -> int:
    """Trace one entry and run the three jaxpr rules against its
    declared contract. Returns checks performed."""
    import jax

    fn, args = entry.build()
    closed = jax.make_jaxpr(fn)(*args)
    info = analyze_program(closed)
    checks = 0
    scope = entry.entry
    path = entry.relpath

    # undeclared-collective: every collective axis must be in the
    # global vocabulary AND the module's declared axes
    seen_axes: Set[str] = set()
    for prim, axis in sorted(info["collectives"]):
        if axis in seen_axes:
            continue
        seen_axes.add(axis)
        checks += 1
        if axis not in MESH_AXES:
            findings.append(Finding(
                "undeclared-collective", path, line, scope,
                f"traced {entry.entry}: {prim} over axis {axis!r}, "
                f"which is outside the MESH_AXES vocabulary "
                f"{MESH_AXES}"))
        elif axis not in contract.mesh_axes:
            findings.append(Finding(
                "undeclared-collective", path, line, scope,
                f"traced {entry.entry}: {prim} over axis {axis!r}, "
                "which this module's PLACEMENT_CONTRACT does not "
                f"declare (mesh_axes: {sorted(contract.mesh_axes)})"))

    # placement-drift: declared class vs established placement,
    # compared over the DECLARED vocabulary (off-vocabulary axes are
    # the undeclared-collective rule's story, not drift)
    checks += 1
    declared = set() if want == REPLICATED else {want}
    established = info["axes"] & set(contract.mesh_axes)
    extra = established - declared
    missing = declared - info["axes"]
    if extra:
        findings.append(Finding(
            "placement-drift", path, line, scope,
            f"traced {entry.entry} establishes placement over "
            f"{sorted(extra)} but its contract declares "
            f"{want!r} — the declaration and the lowered program "
            "disagree"))
    elif missing:
        findings.append(Finding(
            "placement-drift", path, line, scope,
            f"traced {entry.entry} declares placement over "
            f"{sorted(missing)} but the traced program establishes "
            "none of it — a stale declaration or a silently "
            "unsharded program"))

    # replicated-large-buffer: a big operand entering fully replicated
    # with no explicit "replicated" holding declaration anywhere in
    # the module (the accidental-pool-replication trap)
    for shape, dtype, nbytes in info["replicated_in"]:
        checks += 1
        if nbytes >= threshold and not contract.has_replicated_holding():
            findings.append(Finding(
                "replicated-large-buffer", path, line, scope,
                f"traced {entry.entry}: operand {shape}/{dtype} "
                f"({nbytes} bytes) enters the shard_map fully "
                "replicated and the module declares no explicit "
                "\"replicated\" holding — every device pays its full "
                "footprint (declare 'holding:<name>': \"replicated\" "
                "or shard it)"))
    return checks


# -- the pass ----------------------------------------------------------------


_SCOPE_PREFIXES = ("llm_sharding_demo_tpu/parallel/",
                   "llm_sharding_demo_tpu/ops/",
                   "llm_sharding_demo_tpu/runtime/",
                   "llm_sharding_demo_tpu/models/")


def run_placement(root: str, paths: Optional[List[str]] = None,
                  traced: Optional[Sequence[TracedPlacement]] = None,
                  trace: bool = True,
                  threshold: int = DEFAULT_REPLICATED_THRESHOLD,
                  ) -> Tuple[List[Finding], dict]:
    """The whole static pass -> (findings, summary). ``summary``
    carries ``placement_checks`` (contract/descriptor validations +
    liveness checks + hot-loop scans + traced-rule evaluations — the
    vacuity guard on the pass itself), ``placement_contracts``
    (per-module live declaration count) and ``vacuous`` (modules whose
    contract resolves to zero live holdings/entries — the strict
    driver fails these). ``paths`` / ``traced`` / ``threshold`` are
    injectable for rule fixtures; ``trace=False`` (lint-only mode)
    keeps the pass jax-free."""
    findings: List[Finding] = []
    checks = 0
    contracts: Dict[str, int] = {}
    vacuous: List[str] = []

    scan_paths = paths if paths is not None else L.iter_sources(root)
    mods: Dict[str, L.ModuleInfo] = {}
    for path in scan_paths:
        mod = L.index_module(path, root)
        if mod is not None:
            mods[mod.relpath] = mod

    contract_by_mod: Dict[str, _Contract] = {}
    for relpath, mod in sorted(mods.items()):
        in_scope = relpath.startswith(_SCOPE_PREFIXES) or paths is not None
        contract = _parse_contract(mod, findings)
        desc = _parse_descriptor(mod, findings)
        if contract is None and desc is None:
            if in_scope:
                coll_line = _module_issues_collectives(mod)
                if coll_line is not None:
                    checks += 1
                    findings.append(Finding(
                        "placement-drift", relpath, coll_line, "<module>",
                        "module issues manual collectives (ppermute) "
                        "but declares no PLACEMENT_CONTRACT — placement "
                        "must be declared, not implied (docs/"
                        "ARCHITECTURE.md 'Placement discipline')"))
            continue
        live = 0
        if contract is not None:
            checks += 1
            contract_by_mod[relpath] = contract
            holding_lines = _holding_sites(mod)
            for name in sorted(contract.holdings):
                checks += 1
                if name in holding_lines:
                    live += 1
                else:
                    findings.append(Finding(
                        "placement-drift", relpath, contract.line,
                        f"holding:{name}",
                        f"PLACEMENT_CONTRACT declares holding {name!r} "
                        "but the module assigns no such attribute "
                        "(stale declaration)"))
            for name in sorted(contract.entries):
                checks += 1
                if _resolve_entry_fn(mod, name) is not None:
                    live += 1
                else:
                    findings.append(Finding(
                        "placement-drift", relpath, contract.line,
                        f"entry:{name}",
                        f"PLACEMENT_CONTRACT declares entry {name!r} "
                        "but no such function exists in this module "
                        "(stale declaration)"))
            # string-literal collective axes against the vocabulary
            for cline, prim, axis in _collective_calls(mod):
                if axis is None:
                    continue
                checks += 1
                if axis not in MESH_AXES:
                    findings.append(Finding(
                        "undeclared-collective", relpath, cline,
                        "<module>",
                        f"{prim} over axis {axis!r}, which is outside "
                        f"the MESH_AXES vocabulary {MESH_AXES}"))
                elif axis not in contract.mesh_axes:
                    findings.append(Finding(
                        "undeclared-collective", relpath, cline,
                        "<module>",
                        f"{prim} over axis {axis!r}, which this "
                        "module's PLACEMENT_CONTRACT does not declare "
                        f"(mesh_axes: {sorted(contract.mesh_axes)})"))
        if desc is not None:
            checks += 1
            live += len(desc)
        if contract is not None or desc:
            contracts[relpath] = live
            if live == 0:
                vacuous.append(relpath)

    # hot-path-reshard: scan every declared decode hot loop
    for relpath, mod in sorted(mods.items()):
        for qual in sorted(mod.declared_hot_loops):
            name = qual.rsplit(".", 1)[-1]
            fn = _resolve_entry_fn(mod, name)
            if fn is None:
                continue  # the lint pass owns stale hot-loop findings
            checks += 1
            for rline, spelling in _reshard_sites(fn):
                findings.append(Finding(
                    "hot-path-reshard", relpath, rline, qual,
                    f"{spelling} inside decode hot loop {qual!r} — an "
                    "implicit per-token resharding (move placement to "
                    "setup, or baseline the decision with "
                    "justification)"))

    # jaxpr half
    if trace:
        for t in (traced if traced is not None else traced_placements()):
            contract = contract_by_mod.get(t.relpath)
            checks += 1
            if contract is None or t.entry not in contract.entries:
                findings.append(Finding(
                    "placement-drift", t.relpath, 1, t.entry,
                    f"traced entry point {t.entry!r} has no "
                    "PLACEMENT_CONTRACT 'entry:' row — its placement "
                    "is unreviewable"))
                continue
            mod = mods.get(t.relpath)
            fn_node = (_resolve_entry_fn(mod, t.entry)
                       if mod is not None else None)
            line = getattr(fn_node, "lineno", contract.line)
            checks += _check_traced(t, contract, contract.entries[t.entry],
                                    line, threshold, findings)

    summary = {
        "placement_checks": checks,
        "placement_contracts": contracts,
        "vacuous": sorted(vacuous),
    }
    return (sorted(findings, key=lambda f: (f.path, f.line, f.rule)),
            summary)
