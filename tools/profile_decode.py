"""Profile the batched decode step on the real bench chip.

Round-3 investigation of VERDICT.md weak #1: cfg3 (GPT-2 124M, bs=8,
bf16) measured ~2.0 ms/step vs 0.51 ms/step at bs=1 on a weight-bound
workload (248 MB bf16 weights/step) — ~4x where theory says ~1.5x
(the extra KV-cache read traffic at bs=8/max_seq=528 is ~156 MB).

Experiments (all chained-scan programs closed by a host fetch; marginal
over two window sizes so the tunnel's fixed ~100 ms sync cost cancels —
see bench.py marginal_seconds):

  A. batch sweep at max_seq=528           — the headline curve
  B. max_seq sweep at bs=8                — cache-read-traffic hypothesis
  C. component ablation at bs=1/8:
       full step | no-attention (weights-only floor) | no-head | attn-only

Usage: python tools/profile_decode.py [--quick]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from llm_sharding_demo_tpu.models import gpt2
from llm_sharding_demo_tpu.ops.attention import cached_attention
from llm_sharding_demo_tpu.ops.layers import gelu_new, layer_norm, linear


def _fetch(x):
    np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0])


def marginal(time_window, n1=32, n2=256, reps=3):
    time_window(n1), time_window(n2)
    t1 = min(time_window(n1) for _ in range(reps))
    t2 = min(time_window(n2) for _ in range(reps))
    return (t2 - t1) / (n2 - n1)


CFG = gpt2.CONFIGS["gpt2"]


def decode_step_fn(params, config, variant: str):
    """One cached decode step, with pieces knocked out per ``variant``."""
    eps = config.layer_norm_epsilon
    n_head = config.n_head

    def step(token, cache):
        h = gpt2.embed(params, token[:, None], cache.length)
        offset = cache.length

        def body(carry, xs):
            layer_params, ck, cv = xs
            a = layer_norm(carry, layer_params["ln_1"]["scale"],
                           layer_params["ln_1"]["bias"], eps)
            qkv = linear(a, layer_params["attn"]["c_attn"]["kernel"],
                         layer_params["attn"]["c_attn"]["bias"])
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q, k, v = (gpt2.split_heads(x, n_head) for x in (q, k, v))
            if variant == "no_attn":
                attn_out, new_ck, new_cv = q, ck, cv
            else:
                attn_out, new_ck, new_cv = cached_attention(
                    q, k, v, ck, cv, offset)
            attn_out = linear(gpt2.merge_heads(attn_out),
                              layer_params["attn"]["c_proj"]["kernel"],
                              layer_params["attn"]["c_proj"]["bias"])
            hh = carry + attn_out
            if variant == "attn_only":
                m = 0.0
            else:
                mm = layer_norm(hh, layer_params["ln_2"]["scale"],
                                layer_params["ln_2"]["bias"], eps)
                m = linear(gelu_new(linear(
                    mm, layer_params["mlp"]["c_fc"]["kernel"],
                    layer_params["mlp"]["c_fc"]["bias"])),
                    layer_params["mlp"]["c_proj"]["kernel"],
                    layer_params["mlp"]["c_proj"]["bias"])
            return hh + m, (new_ck, new_cv)

        blocks = params["blocks"]
        h, (nk, nv) = jax.lax.scan(body, h, (blocks, cache.k, cache.v))
        from llm_sharding_demo_tpu.ops.attention import KVCache
        cache = KVCache(k=nk, v=nv, length=cache.length + 1)
        if variant == "no_head":
            nxt = h[:, -1, 0].astype(jnp.int32) % config.vocab_size
        else:
            logits = gpt2.final_logits(params, h, eps)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return nxt, cache

    return step


def time_variant(params, config, batch, max_seq, variant, quick=False):
    step = decode_step_fn(params, config, variant)

    @functools.partial(jax.jit, static_argnames=("n",), donate_argnums=(1,))
    def run(token, cache, n):
        def body(carry, _):
            token, cache = carry
            nxt, cache = step(token, cache)
            return (nxt, cache), None
        (token, cache), _ = jax.lax.scan(body, (token, cache), None, length=n)
        return token, cache

    token = jnp.zeros((batch,), jnp.int32)

    def window(n):
        cache = gpt2.make_cache(config, batch, max_seq, jnp.bfloat16)
        t0 = time.perf_counter()
        out, c = run(token, cache, n)
        _fetch(out)
        return time.perf_counter() - t0

    n1, n2 = (16, 64) if quick else (32, 256)
    return marginal(window, n1, n2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)

    rows = []

    def report(name, batch, max_seq, variant):
        ms = time_variant(params, CFG, batch, max_seq, variant,
                          args.quick) * 1e3
        rows.append((name, batch, max_seq, variant, ms))
        print(f"{name:34s} bs={batch} max_seq={max_seq:5d} "
              f"{variant:10s} {ms:8.3f} ms/step "
              f"({batch / ms * 1e3:8.0f} tok/s)", flush=True)

    for b in (1, 8):
        report("A_batch_sweep", b, 528, "full")
    for ms_ in (64, 528, 1024):
        report("B_cache_sweep", 8, ms_, "full")
    for v in ("no_attn", "no_head", "attn_only"):
        report("C_ablate_bs8", 8, 528, v)
        report("C_ablate_bs1", 1, 528, v)


if __name__ == "__main__":
    main()
