"""Decode-step profiling probes for the tunneled bench chip.

These probes produced the round-3 findings (see ops/decode_attention.py
and the git log):

1. XLA will NOT update a KV cache in place when the freshly written
   buffer feeds a dot in the same loop iteration — every
   ``dynamic_update_slice``+attend decode step materializes a copy of
   the touched buffers (~200-230 GB/s effective vs ~515 GB/s for
   read-only streaming). Donation, ``optimization_barrier``, full
   unrolling, and separate per-layer buffers all measured the same or
   worse.
2. Attention reads over scan **xs** stream at ~515 GB/s; the decode
   kernel's fused-KV DMA blocks reach further still.
3. The LM-head matvec at bs=8 runs at ~800 GB/s — HBM roofline; the
   head was never the batched-decode bottleneck.

Methodology notes that matter on this backend (see also bench.py):
every timing window is ONE dependency-chained compiled program closed
by a host fetch (``block_until_ready`` is not a sync barrier through
the tunnel), and rates are two-point marginals so the fixed ~100 ms
sync cost cancels. Compiles cost ~1-2 min each through the remote
compiler — probes are budgeted in compiles first, math second.

Usage: python tools/profile_decode.py [--probe engine|attention|head]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# THE timing harness lives in bench.py (incl. the non-positive-marginal
# guard for windows drowned by barrier jitter) — reuse, don't re-derive
from bench import _fetch, marginal_seconds


def marginal(window, n1: int, n2: int, reps: int = 3) -> float:
    m = marginal_seconds(window, n1, n2, reps=reps)
    if m is None:
        raise RuntimeError("marginal below the tunnel's timer resolution "
                           "(t2 <= t1); enlarge the windows")
    return m


def probe_engine() -> None:
    """Full decode steps via the real engine (the known-good harness):
    kernel vs XLA path at the cfg3 shape."""
    import bench
    from llm_sharding_demo_tpu.models import gpt2

    for bs in (1, 8):
        out = bench.measure_engine(gpt2.CONFIGS["gpt2"], 16, bs,
                                   "bfloat16", s_b=512)
        ms = out["p50_token_latency_ms"]
        print(f"engine bs={bs}: {ms:.3f} ms/step "
              f"({out['tokens_per_sec']:.0f} tok/s)", flush=True)


def probe_attention() -> None:
    """Isolated cached-attention read patterns at the cfg3 shape —
    reproduces finding 1/2 above."""
    L, B, H, S, hd = 12, 8, 12, 528, 64
    key = jax.random.PRNGKey(0)
    K = jax.random.normal(key, (L, B, H, S, hd), jnp.bfloat16)
    V = jax.random.normal(key, (L, B, H, S, hd), jnp.bfloat16)
    q0 = jax.random.normal(key, (B, H, hd), jnp.bfloat16)
    kn = jax.random.normal(key, (B, H, 1, hd), jnp.bfloat16)
    nbytes = L * B * H * S * hd * 2 * 2

    def attend(h, k, v):
        s = jnp.einsum("bhd,bhkd->bhk", h, k,
                       preferred_element_type=jnp.float32)
        w = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        return h + jnp.einsum("bhk,bhkd->bhd", w, v) * 1e-3

    def stream_step(q, K, V):            # read-only: scan xs streaming
        def body(h, kv):
            k, v = kv
            return attend(h, k, v), None
        h, _ = jax.lax.scan(body, q, (K, V))
        return h, K, V

    def carry_step(q, K, V):             # write-then-read on the carry
        def body(c, li):
            h, K, V = c
            K = jax.lax.dynamic_update_slice(
                K, kn[None] + h[:, :, None, :] * 0, (li, 0, 0, 100, 0))
            V = jax.lax.dynamic_update_slice(V, kn[None], (li, 0, 0, 100, 0))
            k = jax.lax.dynamic_index_in_dim(K, li, 0, keepdims=False)
            v = jax.lax.dynamic_index_in_dim(V, li, 0, keepdims=False)
            return (attend(h, k, v), K, V), None
        (h, K, V), _ = jax.lax.scan(body, (q, K, V), jnp.arange(L))
        return h, K, V

    for name, step in (("stream (read-only)", stream_step),
                       ("carry (write+read)", carry_step)):
        def run_n(n, step=step):
            @jax.jit
            def run(q, K, V):
                def body(c, _):
                    return step(*c), None
                (q, K, V), _ = jax.lax.scan(body, (q, K, V), None, length=n)
                return q
            return run

        compiled = {}

        def window(n):
            if n not in compiled:
                compiled[n] = run_n(n)
            t0 = time.perf_counter()
            _fetch(compiled[n](q0, K, V))
            return time.perf_counter() - t0

        ms = marginal(window, 8, 32) * 1e3
        print(f"attention {name}: {ms:.3f} ms/step, "
              f"{nbytes / (ms / 1e3) / 1e9:.0f} GB/s", flush=True)


def probe_head() -> None:
    """LM-head matvec at bs=8 (finding 3)."""
    w = jax.random.normal(jax.random.PRNGKey(0), (768, 50257), jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 768), jnp.bfloat16)
    compiled = {}

    def run_n(n):
        @jax.jit
        def run(x):
            def body(c, _):
                y = jnp.einsum("bd,dv->bv", c, w,
                               preferred_element_type=jnp.float32)
                return c + (y[:, :768] * 1e-6).astype(c.dtype), None
            c, _ = jax.lax.scan(body, x, None, length=n)
            return c
        return run

    def window(n):
        if n not in compiled:
            compiled[n] = run_n(n)
        t0 = time.perf_counter()
        _fetch(compiled[n](x))
        return time.perf_counter() - t0

    ms = marginal(window, 16, 64) * 1e3
    nbytes = 768 * 50257 * 2
    print(f"head matvec bs=8: {ms:.3f} ms/step, "
          f"{nbytes / (ms / 1e3) / 1e9:.0f} GB/s")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", default="engine",
                    choices=("engine", "attention", "head"))
    args = ap.parse_args()
    {"engine": probe_engine, "attention": probe_attention,
     "head": probe_head}[args.probe]()
