"""bench_diff: perf-regression gating over the bench trajectory.

The driver has journaled a ``BENCH_rNN.json`` row per round since round
1 — and nothing ever read them back: a throughput regression would land
in the trajectory and sit there unflagged. This tool closes that loop:

    python tools/bench_diff.py [--current BENCH_full.json]
                               [--history 'BENCH_r*.json']
                               [--threshold 0.25] [--json]

- **current** is a bench payload (the ``bench.py`` full-matrix artifact:
  headline ``metric``/``value`` plus per-config rows);
- **history** is the committed trajectory (``BENCH_rNN.json`` driver
  rows, each wrapping a ``parsed`` payload; rounds whose payload is
  null/skipped — e.g. the TPU tunnel was down — contribute nothing);
- every numeric metric the two sides share is classified by name
  (throughput-like: higher is better; latency-like: lower is better;
  unclassifiable names are reported but never gated) and compared
  against the LATEST prior value with a relative threshold. A gated
  metric moving past its threshold in the bad direction is a
  regression: nonzero exit, wired into the in-suite driver
  (tests/test_graftscope.py) so a committed artifact that regresses the
  trajectory fails CI rather than aging silently.

The default threshold is deliberately loose (25%): the bench chip rides
a tunnel and round-to-round noise is real; the gate exists for
step-function regressions (a donated-buffer copy re-appearing, a
compile storm, a scheduler serialization), not single-digit drift —
the drift story is the journaled rows themselves.

bench.py journals the verdict as the ``bench_diff`` config row beside
``graftcheck_static_analysis``, so every committed matrix carries its
own comparison against the trajectory that preceded it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_THRESHOLD = 0.25

# per-metric threshold overrides (relative). The headline value rides a
# tunnel whose RTT dominates sub-second workloads — keep its gate loose.
THRESHOLDS: Dict[str, float] = {
    "headline.value": 0.35,
}

# name-suffix/substring classification: which direction is "worse".
_HIGHER_BETTER = ("tokens_per_sec", "tokens_per_second", "speedup",
                  "vs_baseline", "mfu", "cache_speedup",
                  "accepted_tokens_per_verify", "success_rate",
                  # timeline_overhead row (grafttime): a slower event
                  # bus regresses DOWNWARD in emit throughput
                  "events_per_sec",
                  # graftload rows: goodput-under-SLO and declared-SLO
                  # attainment regress DOWNWARD (fewer requests inside
                  # their declared budgets)
                  "goodput", "slo_attainment",
                  # graftfleet rows: a regressing router scatters warm
                  # prefixes (affinity hit rate drops) and an emptier
                  # batch at the same offered load means admission or
                  # scheduling got worse, not better
                  "affinity_hit_rate", "batch_occupancy",
                  # numerics_oracle row (graftnum): greedy argmax
                  # agreement of an approximate path with its f32
                  # sibling regresses DOWNWARD (checked before the
                  # lower-better list so the metric never falls through
                  # to a latency-ish suffix match)
                  "top1_agreement",
                  # kv_quant_capacity row (quantized KV blocks): rows
                  # admitted before the first preemption, the int8/f32
                  # admitted-row ratio at equal pool bytes, and the
                  # prefix-store depth all regress DOWNWARD — fewer
                  # resident rows per HBM byte
                  "before_first_preemption", "capacity_ratio",
                  "prefix_store_depth",
                  # tiered_kv_depth row (grafttier): the ledger-measured
                  # host/device depth ratio and the replayed-epoch
                  # prefix/promoted hit rates all regress DOWNWARD —
                  # less prefix state resident per device byte, or a
                  # tier that stopped answering affinity hits (the
                  # promote-stall side is the _ms suffix, lower-better)
                  "depth_ratio", "prefix_hit_rate", "promoted_hit_rate",
                  # trend_detection row (grafttrend): the seeded burst
                  # is pinned, so a reducer that stops tripping on it
                  # went blind — detection regresses DOWNWARD
                  "burst_detected")
_LOWER_BETTER = ("_ms", "latency", "step_ms", "prefill_ms",
                 # traffic_mix occupancy join: deeper queues at the
                 # same offered rate = the serving stack fell behind
                 "queue_depth",
                 # plan_switch row (graftwatch): compiled programs
                 # minted past the pre-certified plan set — the pinned
                 # invariant is ZERO, so any upward drift is a
                 # certified-envelope leak, the worst kind of
                 # regression a live re-planner can have
                 "recompile",
                 # timeline_overhead row (grafttime): the bus-armed vs
                 # bus-off wall ratio drifting up means the always-on
                 # timeline started taxing the decode path
                 "overhead_factor",
                 # numerics_oracle row (graftnum): per-position logit
                 # MSE of an approximate path vs its f32 sibling —
                 # upward drift means the quantizer/bf16 discipline
                 # lost precision (also caught by the "_ms" suffix,
                 # but the explicit name documents the intent)
                 "logit_mse",
                 # hbm_attribution row (graftmem): |measured/modeled - 1|
                 # byte drift between the live ledger and the cost
                 # model's aval arithmetic — f32 configs pin at exactly
                 # 0.0 and the int8 pool's designed savings is constant
                 # for fixed geometry, so ANY upward movement means the
                 # ledger lost an allocation or the model lost a term
                 "drift",
                 # trend_detection row (grafttrend): alerts fired
                 # during the QUIET serial phases of the pinned mix —
                 # a watch that pages on healthy traffic is worse than
                 # no watch at all
                 "false_positive")
# environment properties, not code performance: the tunnel's RTT, the
# reference CPU's own rate, and the attribution run's host-dependent
# byte rates vary by machine/route — comparing them across rounds would
# gate the weather, not the code (they still ride the rows report-only)
_NOT_GATED = ("transfer_rtt", "rtt_bound", "ref_cpu", "baseline_cpu",
              "implied_bytes_per_second", "seconds_per_token")


def classify(field: str) -> Optional[str]:
    """'higher' | 'lower' | None (not gated). ``headline.value`` is the
    round's tokens/sec headline — always gated higher-better."""
    f = field.lower()
    if any(s in f for s in _NOT_GATED):
        return None
    if f in ("value", "headline.value"):
        return "higher"
    if any(s in f for s in _HIGHER_BETTER):
        return "higher"
    if any(s in f for s in _LOWER_BETTER):
        return "lower"
    return None


def extract_metrics(payload: dict) -> Dict[str, float]:
    """Flatten a bench payload into ``{"cfg.field": value}`` numeric
    rows plus the headline ``headline.value``. Skips error/skip rows
    and non-scalar fields."""
    out: Dict[str, float] = {}
    if not isinstance(payload, dict):
        return out
    for field, v in payload.items():
        # top-level numeric fields are the round's headline block
        # (value, vs_baseline, latency context); early rounds carried
        # their whole matrix there, so flattening them keeps the oldest
        # trajectory comparable
        if field in ("configs", "n", "batch"):
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[f"headline.{field}"] = float(v)
    for cfg in payload.get("configs") or ():
        if not isinstance(cfg, dict):
            continue
        name = cfg.get("name")
        if not name or cfg.get("error") or cfg.get("skipped"):
            continue
        for field, val in cfg.items():
            if field in ("name", "note", "metrics_delta"):
                continue
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                out[f"{name}.{field}"] = float(val)
            elif field == "workloads" and isinstance(val, list):
                # nested per-workload rows (the graftscope_attribution
                # journal shape): flatten so the drift trajectory is
                # comparable across rounds (host-dependent rates stay
                # report-only via _NOT_GATED)
                for row in val:
                    if not isinstance(row, dict):
                        continue
                    wl = row.get("workload")
                    for f2, v2 in row.items():
                        if wl and isinstance(v2, (int, float)) \
                                and not isinstance(v2, bool):
                            out[f"{name}.{wl}.{f2}"] = float(v2)
    return out


def skipped_configs(payload: dict) -> Dict[str, str]:
    """Config names whose row was SKIPPED with a reason (e.g. the TPU
    tunnel was down). These rows contribute no gated metrics — which
    used to be silent: a trajectory where every on-chip row skips
    still exited 0 and read as "gated". ``compare`` now reports them
    as ``ungated_rows`` with their reasons, and ``--no-skips`` turns
    any of them into a nonzero exit so CI can notice the tunnel is
    down instead of green-lighting an ungated run."""
    out: Dict[str, str] = {}
    for cfg in (payload or {}).get("configs") or ():
        if isinstance(cfg, dict) and cfg.get("name") \
                and cfg.get("skipped"):
            out[cfg["name"]] = str(cfg["skipped"])
    return out


def error_configs(payload: dict) -> set:
    """Config names whose row ERRORED — what ``compare`` uses to turn a
    config that stopped producing numbers into a finding instead of a
    silent gap. Skip rows (``skipped``: the tunnel/chip was down) are
    deliberately excluded: a skip is environment, not a crash, and the
    trajectory is honestly full of them."""
    out = set()
    for cfg in (payload or {}).get("configs") or ():
        if isinstance(cfg, dict) and cfg.get("name") and cfg.get("error"):
            out.add(cfg["name"])
    return out


def load_history(paths: List[str]) -> List[Tuple[str, Dict[str, float]]]:
    """[(label, metrics)] oldest-first. Driver rows wrap the payload in
    ``parsed`` (null when the round's output didn't parse — those rows
    contribute nothing, honestly)."""
    rows: List[Tuple[int, str, Dict[str, float]]] = []
    for i, path in enumerate(sorted(paths)):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        wrapped = isinstance(doc, dict) and "parsed" in doc
        payload = doc.get("parsed") if wrapped else doc
        metrics = extract_metrics(payload or {})
        # only driver rows carry a round number; a raw payload's "n"
        # would be some unrelated field (e.g. a token count) and
        # sorting on it would misorder the trajectory — raw files keep
        # their sorted-glob position
        n = doc.get("n", i) if wrapped else i
        if metrics:
            rows.append((int(n), os.path.basename(path), metrics))
    rows.sort()
    return [(label, m) for _, label, m in rows]


def compare(current: Dict[str, float],
            history: List[Tuple[str, Dict[str, float]]],
            threshold: float = DEFAULT_THRESHOLD,
            current_errors: Optional[set] = None,
            current_skips: Optional[Dict[str, str]] = None) -> dict:
    """Join current metrics against the latest prior value per metric.
    Returns the JSON-able verdict payload; ``ok`` is False iff any
    gated metric regressed past its threshold — or a config that
    produced gated numbers in the latest prior run now ERRORS
    (``current_errors``): a config dying outright is the worst
    regression, not a silent gap in the join."""
    rows: List[dict] = []
    regressions: List[str] = []
    for name in sorted(current_errors or ()):
        prior_fields = sorted(
            m for label, metrics in history[-1:] for m in metrics
            if m.startswith(name + ".")
            and classify(m.rpartition(".")[2]) is not None)
        if prior_fields:
            rows.append({"metric": name, "status": "regression",
                         "error": "config errored this run; its gated "
                                  f"metrics vanished: {prior_fields}"})
            regressions.append(name)
    for metric in sorted(current):
        prior = prior_run = None
        for label, metrics in reversed(history):
            if metrics.get(metric) is not None:
                prior, prior_run = metrics[metric], label
                break
        if prior is None:
            rows.append({"metric": metric, "current": current[metric],
                         "status": "no-prior"})
            continue
        direction = classify(metric.rpartition(".")[2] or metric)
        thr = THRESHOLDS.get(metric, threshold)
        delta = (current[metric] - prior) / abs(prior) if prior else 0.0
        row = {"metric": metric, "current": current[metric],
               "prior": prior, "prior_run": prior_run,
               "delta_pct": round(delta * 100, 2)}
        if direction is None:
            row["status"] = "not-gated"
        elif (direction == "higher" and delta < -thr) \
                or (direction == "lower" and delta > thr):
            row["status"] = "regression"
            row["threshold_pct"] = round(thr * 100, 1)
            regressions.append(metric)
        else:
            row["status"] = "ok"
        rows.append(row)
    return {
        "ok": not regressions,
        "threshold": threshold,
        "compared": sum(1 for r in rows if r["status"] in
                        ("ok", "regression")),
        "regressions": regressions,
        # skip-with-reason rows: environment-honest but UNGATED — they
        # never fail the default run, but they must not vanish either
        # (--no-skips promotes their presence to a nonzero exit)
        "ungated_rows": [{"config": name, "reason": reason}
                         for name, reason in
                         sorted((current_skips or {}).items())],
        # the --no-skips verdict as DATA: ok AND nothing ungated — the
        # journaled bench_diff row carries it, so a down TPU tunnel
        # (every on-chip row skip-with-reason) is loud in the row
        # payload itself, not only behind the opt-in CLI flag
        "no_skips_ok": (not regressions) and not (current_skips or {}),
        "history_runs": [label for label, _ in history],
        "rows": rows,
    }


def main(argv: Optional[List[str]] = None) -> int:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(
        prog="python tools/bench_diff.py",
        description="flag perf regressions against the committed "
                    "BENCH_* trajectory (exit 1 on regression)")
    ap.add_argument("--current",
                    default=os.path.join(here, "BENCH_full.json"),
                    help="bench payload to gate (default: the committed "
                    "full matrix)")
    ap.add_argument("--history",
                    default=os.path.join(here, "BENCH_r*.json"),
                    help="glob of prior trajectory rows")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative regression threshold (default 0.25)")
    ap.add_argument("--no-skips", action="store_true",
                    help="exit nonzero when any config row was skipped "
                    "with a reason (ungated_rows) — CI mode: an ungated "
                    "run must not read as a gated one")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    try:
        with open(args.current, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: cannot read --current {args.current}: {e}",
              file=sys.stderr)
        return 2
    payload = doc.get("parsed") if isinstance(doc, dict) \
        and "parsed" in doc else doc
    current = extract_metrics(payload or {})
    history = load_history(glob.glob(args.history))
    verdict = compare(current, history, threshold=args.threshold,
                      current_errors=error_configs(payload or {}),
                      current_skips=skipped_configs(payload or {}))

    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        for r in verdict["rows"]:
            if r["status"] != "regression":
                continue
            if "error" in r:
                print(f"REGRESSION {r['metric']}: {r['error']}")
            else:
                print(f"REGRESSION {r['metric']}: {r['prior']} "
                      f"({r['prior_run']}) -> {r['current']} "
                      f"({r['delta_pct']}% past the "
                      f"{r['threshold_pct']}% gate)")
        for row in verdict["ungated_rows"]:
            print(f"UNGATED {row['config']}: skipped — {row['reason']}")
        print(f"bench_diff: {verdict['compared']} metric(s) compared "
              f"against {len(verdict['history_runs'])} prior run(s), "
              f"{len(verdict['regressions'])} regression(s), "
              f"{len(verdict['ungated_rows'])} ungated skip row(s)")
    if args.no_skips:
        return 0 if verdict["no_skips_ok"] else 1
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
