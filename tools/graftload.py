"""graftload CLI: seeded open-loop load runs against an in-process app.

    python -m tools.graftload [--profiles bursty_chat,agentic]
                              [--seed 0] [--requests 24]
                              [--rate-scales 1.0,2.0] [--mode open]
                              [--json] [--preview N]

Builds a tiny randomly-initialized GPT-2 serving app (pooled iteration
scheduler — the production composition serving/app.py wires for
BATCH_MODE=iter + KV_POOL_BLOCKS) entirely in-process, then drives the
selected ``loadgen.PROFILES`` through the seeded open-loop generator
and prints one Pareto/goodput row per ``(profile, rate_scale)`` — the
same rows bench.py journals as ``graftload_pareto`` /
``slo_attainment`` and ``tools/bench_diff.py`` gates.

``--preview N`` prints the first N scheduled arrivals of each profile
WITHOUT running them — the replay-identity debugging view (the
schedule is a pure function of ``(seed, profile, k)``; two invocations
with the same seed print byte-identical previews).

``--mode closed --width W`` runs the closed-loop comparison generator
(W workers, back-to-back). It exists to demonstrate WHY the default is
open-loop: at saturation the closed loop throttles itself and
under-reports tail latency (pinned by tests/test_graftload.py) — do
not gate on closed-loop numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_demo_app(max_seq: int = 256, max_batch: int = 4,
                   kv_pool_blocks: int = 0, kv_block_size: int = 16,
                   recorder_capacity: int = 1024,
                   continuous: bool = False,
                   auto_plan_traffic: str = ""):
    """(client, recorder, registry) for a tiny in-process pooled
    serving app — the graftload CLI/bench target. ``kv_pool_blocks=0``
    sizes the pool to hold ``max_batch`` full-length rows.
    ``max_batch=1`` serves the solo paged runner (admission mode);
    ``continuous=True`` arms graftwatch's AUTO_PLAN_CONTINUOUS plan
    switching over the same composition (the bench ``plan_switch``
    row's target), and ``auto_plan_traffic`` (costmodel.parse_traffic
    syntax, e.g. ``"16/8x3,24/8x3"``) declares the traffic classes the
    plan set is certified against — pass the byte-lengths of the
    schedule you are about to drive and the certified program bounds
    cover the whole run."""
    from llm_sharding_demo_tpu.fleet.harness import demo_model
    from llm_sharding_demo_tpu.serving.app import create_app
    from llm_sharding_demo_tpu.serving.http import TestClient
    from llm_sharding_demo_tpu.serving.tokenizer import ByteTokenizer
    from llm_sharding_demo_tpu.utils.config import ServingConfig
    from llm_sharding_demo_tpu.utils.metrics import MetricsRegistry
    from llm_sharding_demo_tpu.utils.tracing import FlightRecorder

    cfg_model, params = demo_model(max_seq)
    if kv_pool_blocks <= 0:
        kv_pool_blocks = max(max_batch, 2) * (-(-max_seq // kv_block_size))
    cfg = ServingConfig(model_id="graftload-demo",
                        shard_role="coordinator", max_seq=max_seq,
                        boundaries=(1,), max_batch=max_batch,
                        batch_mode="iter" if max_batch > 1
                        else "admission", batch_wait_ms=10.0,
                        kv_pool_blocks=kv_pool_blocks,
                        kv_block_size=kv_block_size,
                        auto_plan_continuous=continuous,
                        auto_plan_traffic=auto_plan_traffic
                        if continuous else "")
    recorder = FlightRecorder(capacity=recorder_capacity)
    registry = MetricsRegistry()
    app = create_app(cfg, model=(cfg_model, params),
                     tokenizer=ByteTokenizer(), registry=registry,
                     recorder=recorder)
    return TestClient(app), recorder, registry


def run_profiles(client, recorder, profiles: List[str], seed: int,
                 n: int, rate_scales: List[float], mode: str,
                 width: int) -> dict:
    from llm_sharding_demo_tpu import loadgen

    reports = []
    for name in profiles:
        prof = loadgen.profile(name)
        for scale in rate_scales:
            reports.append(loadgen.run_load(
                client, prof, seed=seed, n=n, rate_scale=scale,
                mode=mode, width=width, recorder=recorder))
    return {
        "seed": seed,
        "requests_per_run": n,
        "mode": mode,
        "pareto": [loadgen.pareto_row(r) for r in reports],
        "slo_attainment": [loadgen.slo_row(r) for r in reports],
        "occupancy": reports[-1]["occupancy"] if reports else {},
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftload",
        description="seeded open-loop load harness: Pareto + "
                    "goodput-under-SLO rows against the in-process "
                    "serving app")
    ap.add_argument("--profiles", default="bursty_chat,agentic",
                    help="comma-separated loadgen.PROFILES names")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests", type=int, default=24,
                    help="arrivals per (profile, rate_scale) run")
    ap.add_argument("--rate-scales", default="1.0",
                    help="comma-separated multipliers of each "
                    "profile's declared rate (a sweep traces the "
                    "Pareto front)")
    ap.add_argument("--mode", default="open",
                    choices=("open", "closed", "serial"))
    ap.add_argument("--width", type=int, default=4,
                    help="closed-loop worker count (mode=closed)")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-seq", type=int, default=256)
    ap.add_argument("--pool-blocks", type=int, default=0,
                    help="KV pool blocks (0: sized for max_batch "
                    "full rows)")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--preview", type=int, default=0,
                    help="print the first N scheduled arrivals per "
                    "profile and exit (no load run)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if here not in sys.path:
        sys.path.insert(0, here)

    from llm_sharding_demo_tpu import loadgen

    names = [p.strip() for p in args.profiles.split(",") if p.strip()]
    for name in names:
        loadgen.profile(name)                 # fail fast on typos

    if args.preview:
        out = {name: [a.to_dict() for a in
                      loadgen.schedule(loadgen.profile(name), args.seed,
                                       args.preview)]
               for name in names}
        print(json.dumps(out, indent=None if args.json else 2,
                         sort_keys=True))
        return 0

    scales = [float(s) for s in args.rate_scales.split(",") if s.strip()]
    client, recorder, _registry = build_demo_app(
        max_seq=args.max_seq, max_batch=args.max_batch,
        kv_pool_blocks=args.pool_blocks, kv_block_size=args.block_size,
        recorder_capacity=max(args.requests * len(names) * len(scales),
                              64))
    payload = run_profiles(client, recorder, names, args.seed,
                           args.requests, scales, args.mode, args.width)

    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0
    print(f"graftload: seed {args.seed}, {args.requests} arrivals per "
          f"run, mode {args.mode}")
    for row in payload["pareto"]:
        print(f"  {row['profile']:<14} x{row['rate_scale']:<4} "
              f"offered {row['offered_rps']:>6} rps  "
              f"tput {row['throughput_tokens_per_sec']:>8} tok/s  "
              f"p99 {row['p99_e2e_ms']:>8} ms  "
              f"good {row['goodput_fraction']:>6}  "
              f"shed {row['shed_429']}+{row['shed_503']}  "
              f"miss {row['deadline_misses']}")
    for row in payload["slo_attainment"]:
        misses = [m for m, r in row["slo"].items() if not r["attained"]]
        print(f"  {row['profile']:<14} SLO attainment "
              f"{row['slo_attainment']}"
              + (f"  MISSED: {misses}" if misses else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
