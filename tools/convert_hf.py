"""CLI: HuggingFace GPT-2/LLaMA checkpoint -> Orbax checkpoint directory.

One-time conversion so serving/training pods never need the HF hub or
torch (the reference instead downloads full HF weights into every pod at
import time, reference server.py:40-42). Run wherever the HF model is
reachable (hub or local cache/path):

    python tools/convert_hf.py gpt2 /ckpt/gpt2
    python tools/convert_hf.py /path/to/local/hf/dir /ckpt/my-model

then point serving at it:  CHECKPOINT_DIR=/ckpt/gpt2
"""

from __future__ import annotations

import argparse
import sys


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_id", help="HF model id or local HF dir")
    parser.add_argument("out_dir", help="Orbax checkpoint directory to write")
    parser.add_argument("--dtype", default="float32",
                        choices=("float32", "bfloat16"))
    args = parser.parse_args()

    import jax.numpy as jnp
    from transformers import AutoModelForCausalLM

    from llm_sharding_demo_tpu.models.hf_convert import (
        llama_params_from_hf_model, params_from_hf_model)
    from llm_sharding_demo_tpu.utils import checkpoint as ckpt

    dtype = jnp.float32 if args.dtype == "float32" else jnp.bfloat16
    print(f"loading HF model {args.model_id} ...", flush=True)
    model = AutoModelForCausalLM.from_pretrained(args.model_id)
    model.eval()
    if getattr(model.config, "model_type", "gpt2") == "llama":
        config, params = llama_params_from_hf_model(model, dtype=dtype)
    else:
        config, params = params_from_hf_model(model, dtype=dtype)
    print(f"converted: {config}", flush=True)
    ckpt.save(args.out_dir, params, config)
    print(f"wrote Orbax checkpoint to {args.out_dir}")

    # Ship the tokenizer assets inside the checkpoint so air-gapped pods
    # never fall back to the byte-level tokenizer (wrong vocab for GPT-2 —
    # serving.tokenizer warns, but the real fix is having the files).
    try:
        import os

        from transformers import AutoTokenizer

        from llm_sharding_demo_tpu.serving.tokenizer import TOKENIZER_SUBDIR
        tok = AutoTokenizer.from_pretrained(args.model_id)
        tok_dir = os.path.join(args.out_dir, TOKENIZER_SUBDIR)
        tok.save_pretrained(tok_dir)
        print(f"wrote tokenizer assets to {tok_dir}")
    except Exception as e:
        print(f"WARNING: could not save tokenizer for {args.model_id} ({e}); "
              "serving will fall back to HF cache or bytes", flush=True)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, ".")
    sys.exit(main())
