"""Thin compatibility shim over ``tools/graftcheck/metric_catalog.py``.

The metric-name catalog lint (PR 2) is now a graftcheck rule so there is
ONE lint entry point (``python -m tools.graftcheck``). This module keeps
the old CLI and the old import surface (``find_violations`` /
``_iter_sources`` / ``main``, used by tests/test_check_metrics.py and any
existing automation) working unchanged.
"""

from __future__ import annotations

import os
import sys

try:                                    # imported as tools.check_metrics
    from .graftcheck import metric_catalog as _impl
except ImportError:                     # imported as top-level check_metrics
    _here = os.path.dirname(os.path.abspath(__file__))
    _added = _here not in sys.path
    if _added:
        sys.path.insert(0, _here)
    try:
        from graftcheck import metric_catalog as _impl
    finally:
        if _added:                      # scoped insert, same leak-class
            try:                        # hygiene as the original tool
                sys.path.remove(_here)
            except ValueError:
                pass

_CALL_RE = _impl._CALL_RE
_TIMED_RE = _impl._TIMED_RE
_KIND_OF_CALL = _impl._KIND_OF_CALL
_iter_sources = _impl._iter_sources
find_violations = _impl.find_violations


def main(argv=None) -> int:
    # default root resolves relative to THIS file (tools/ -> repo root),
    # exactly as the pre-shim CLI did
    root = (argv or sys.argv[1:]
            or [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))])
    return _impl.main([root[0]])


if __name__ == "__main__":
    sys.exit(main())
