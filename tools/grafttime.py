"""grafttime export CLI: captured timeline streams -> Chrome trace JSON.

Usage:

    python -m tools.grafttime export --input timeline.json [-o out.json]
    curl .../debug/timeline?rid=abc > timeline.json \\
        && python -m tools.grafttime export --input timeline.json

Accepted input shapes (all produced by the runtime itself):

- a ``GET /debug/timeline`` payload (``{"events": [...], "clock": ...}``),
- a black-box dump (``grafttime.blackbox`` — the same payload plus
  ``reason``/``rid``; ``$GRAFTTIME_DIR/grafttime_blackbox_*.json``),
- a bare event list (``[...]``).

The export is validated against the Chrome Trace Event Format schema
(``grafttime.validate_chrome``) before it is written: exit 0 on a valid
trace, 1 when validation fails (the problems print to stderr), 2 on
unreadable/unrecognized input. ``--input -`` reads stdin. Load the
output in ``chrome://tracing`` or https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_events(doc) -> list:
    """Pull the event list out of any accepted input shape; raises
    ValueError on anything else (a typed refusal, not a guess)."""
    if isinstance(doc, list):
        events = doc
    elif isinstance(doc, dict) and isinstance(doc.get("events"), list):
        events = doc["events"]
    else:
        raise ValueError(
            "unrecognized input: want a /debug/timeline payload, a "
            "grafttime black-box dump, or a bare event list")
    for e in events:
        if not isinstance(e, dict) or "kind" not in e or "ts" not in e:
            raise ValueError(
                "event stream entries must be objects with at least "
                f"'kind' and 'ts'; got {e!r}"[:160])
    return events


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(
        prog="python -m tools.grafttime",
        description="unified-timeline tooling (utils/grafttime.py is "
                    "the runtime bus; this converts captured streams "
                    "to Chrome-trace/Perfetto JSON)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ex = sub.add_parser("export", help="timeline stream -> Chrome trace")
    ex.add_argument("--input", "-i", required=True,
                    help="a /debug/timeline payload, black-box dump, or "
                    "bare event list; '-' reads stdin")
    ex.add_argument("--output", "-o", default="-",
                    help="output path ('-' = stdout, the default)")
    args = ap.parse_args(argv)

    from llm_sharding_demo_tpu.utils import grafttime

    try:
        if args.input == "-":
            doc = json.load(sys.stdin)
        else:
            with open(args.input, encoding="utf-8") as f:
                doc = json.load(f)
        events = _load_events(doc)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"grafttime export: cannot read {args.input}: {e}",
              file=sys.stderr)
        return 2

    meta = {}
    if isinstance(doc, dict):
        for k in ("reason", "rid", "clock"):
            if doc.get(k) is not None:
                meta[k] = doc[k]
    payload = grafttime.export_chrome(events, meta=meta)
    problems = grafttime.validate_chrome(payload)
    if problems:
        for p in problems:
            print(f"grafttime export: invalid trace: {p}",
                  file=sys.stderr)
        return 1
    text = json.dumps(payload, default=str)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"grafttime export: {len(events)} event(s) -> "
              f"{args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
