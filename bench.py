"""Benchmark harness: the full BASELINE.json measurement matrix.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "configs"}.
The top-level metric is the headline number (GPT-2 124M single-stream greedy
decode, bf16, on the visible TPU chip); ``configs`` carries every
BASELINE.md row so the matrix has measured values instead of TBDs:

  cfg1  tiny-gpt2, 2-shard pipeline, 20 new tokens (the notebook workload)
  cfg2  GPT-2 124M, 2-shard (6+6) + single-chip engine, single prompt
  cfg3  GPT-2 124M, batch=8 (the reference can only run bs=1 sequentially,
        server.py:137 — its baseline is 8x one stream)
  cfg4  GPT-2 medium, 4-shard pipeline (round-robin on this 1 chip: the
        bench environment exposes a single TPU; stage handoffs still run,
        labeled honestly in the row)
  cfg5  KV-cache incremental decode vs O(n^2) full re-forward per token —
        both measured on THIS framework on-chip, plus the reference's own
        O(n^2) torch CPU loop for scale

Baseline denominators re-measure the reference's decode algorithm
in-process on CPU: a torch GPT-2 re-forwarding the FULL growing sequence
per token with no KV cache (reference server.py:169-181), greedy. No
HTTP/JSON hops are charged to it, so every vs_baseline here is
conservative — the deployed reference is slower than its denominator.

Both sides use random-init weights of the same architecture (no HF hub in
this image; throughput is weight-independent). fp32 engine rows exist
because fp32 is the BASELINE.json greedy-parity mode; bf16 rows are the
TPU-native fast path (fp32 LN/softmax/logits, bf16 weights + KV).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

PROMPT_LEN = 16
# Two-point decode windows: the bench chip sits behind a network tunnel
# where each host<->device transfer costs ~10-15 ms (measured and reported
# as transfer_rtt_ms) and a generate() call makes several. Timing one
# window charges that fixed cost to the tokens; the marginal cost between
# two windows cancels it, giving the steady-state per-token cost the
# hardware actually delivers.
STEPS_A = 64
STEPS_B = 256


def measure_reference_cpu(config, prompt_len: int, new_tokens: int) -> float:
    """tokens/sec of the reference's O(n^2) CPU decode loop (torch)."""
    import torch
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    torch.manual_seed(0)
    model = GPT2LMHeadModel(HFConfig(
        vocab_size=config.vocab_size, n_positions=config.n_positions,
        n_embd=config.n_embd, n_layer=config.n_layer, n_head=config.n_head))
    model.eval()
    ids = list(np.random.default_rng(0).integers(
        0, config.vocab_size, size=(prompt_len,)))
    # warmup one forward (thread pools, allocator)
    with torch.no_grad():
        model(torch.tensor([ids]))
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        with torch.no_grad():
            logits = model(torch.tensor([ids])).logits[0, -1]
        ids.append(int(torch.argmax(logits)))  # greedy parity mode
    dt = time.perf_counter() - t0
    return new_tokens / dt


def measure_dispatch_rtt() -> float:
    """Fixed per-call overhead, ms: one small host->device transfer.

    On the tunneled bench chip, program dispatch is sub-0.1 ms but each
    host<->device copy costs ~10-15 ms; a generate() call makes several
    (prompt up, tokens down, keys), which is the fixed cost the two-point
    marginal timing cancels."""
    import jax.numpy as jnp

    jnp.asarray(np.zeros((1, 256), np.int32)).block_until_ready()  # warmup
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        jnp.asarray(np.zeros((1, 256), np.int32)).block_until_ready()
    return (time.perf_counter() - t0) / n * 1e3


def _two_point(runner, prompt, s_a: int = STEPS_A, s_b: int = STEPS_B) -> dict:
    """Steady-state decode cost via marginal timing between two windows."""
    runner.generate(prompt, s_a)                   # compile window A
    runner.generate(prompt, s_b)                   # compile window B
    ra = runner.generate(prompt, s_a)
    rb = runner.generate(prompt, s_b)
    marginal = ((rb.decode_seconds - ra.decode_seconds)
                / (rb.decode_steps - ra.decode_steps))
    batch = prompt.shape[0]
    return {
        "tokens_per_sec": batch / marginal,
        "p50_token_latency_ms": marginal * 1e3,
        "e2e_tokens_per_sec": rb.tokens_per_second,
        "prefill_ms": rb.prefill_seconds * 1e3,
    }


def measure_engine(config, prompt_len: int, batch: int,
                   dtype_name: str = "float32") -> dict:
    """Single-device engine: jitted prefill + scanned KV-cache decode."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    engine = DecodeEngine(params, config, max_seq=prompt_len + STEPS_B,
                          dtype=dtype)
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, prompt_len))
    return _two_point(engine, prompt)


def measure_pipeline(config, n_stages: int, prompt_len: int,
                     batch: int = 1, dtype_name: str = "float32",
                     two_point: bool = True, new_tokens: int = STEPS_A,
                     ) -> dict:
    """N-shard pipelined decode as a single compiled program per phase.

    With >= n_stages real devices this is the shard_map + ppermute decoder
    (one program, stage weights resident per chip, ICI hops). On the 1-chip
    bench environment it falls back to the staged DecodeEngine: the SAME
    validated stage partition (parallel.partition), composed in one
    program on the one chip — labeled in the row. The host-driven
    PipelineRunner is deliberately not timed here: per-token host
    dispatches over the axon tunnel measure RTT, not the framework."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.parallel.ppdecode import PipelinedDecoder
    from llm_sharding_demo_tpu.parallel.spmd import make_mesh
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    per = config.n_layer // n_stages
    boundaries = [per * i for i in range(1, n_stages)]
    max_seq = prompt_len + (STEPS_B if two_point else new_tokens)
    n_real = len(jax.devices())
    if n_real >= n_stages:
        mesh = make_mesh({"pp": n_stages}, jax.devices()[:n_stages])
        runner = PipelinedDecoder(params, config, mesh, max_seq=max_seq,
                                  dtype=dtype)
        placement = f"ppermute over {n_stages} devices"
    else:
        runner = DecodeEngine(params, config, max_seq=max_seq, dtype=dtype,
                              boundaries=boundaries)
        placement = f"{n_stages} stages fused on {n_real} chip(s)"
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, prompt_len))
    if two_point:
        out = _two_point(runner, prompt)
    else:  # fixed workload (cfg1's mandated 20 tokens): e2e, RTT included
        runner.generate(prompt, new_tokens)        # warmup
        result = runner.generate(prompt, new_tokens)
        out = {
            "tokens_per_sec": result.tokens_per_second,
            "p50_token_latency_ms": result.per_token_latency * 1e3,
        }
    out["placement"] = placement
    return out


def measure_uncached_jax(config, prompt_len: int, new_tokens: int,
                         dtype_name: str = "bfloat16") -> float:
    """Our model WITHOUT the KV cache: re-forward the full fixed-length
    sequence per token (one compile; the reference's O(n^2) algorithm at
    constant shape). Denominator for cfg5's cache-speedup ratio. The
    per-token host dispatches pipeline asynchronously, so tunnel RTT is
    naturally hidden here — comparable with the cached steady-state."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    total = prompt_len + new_tokens

    @jax.jit
    def step(params, ids, t):
        logits = gpt2.forward(params, ids, config)          # [1, total, V]
        nxt = jnp.argmax(logits[0, t - 1]).astype(jnp.int32)
        return jax.lax.dynamic_update_slice(ids, nxt[None, None], (0, t))

    ids = np.zeros((1, total), dtype=np.int32)
    ids[0, :prompt_len] = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(prompt_len,))
    ids = jnp.asarray(ids)
    ids = step(params, ids, prompt_len).block_until_ready()  # warmup/compile
    t0 = time.perf_counter()
    for t in range(prompt_len, total):
        ids = step(params, ids, t)
    ids.block_until_ready()
    dt = time.perf_counter() - t0
    return new_tokens / dt


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="cfg1 only (tiny model) for a fast smoke run")
    args = parser.parse_args()

    from llm_sharding_demo_tpu.models import gpt2

    tiny, g124, gmed = (gpt2.CONFIGS[k]
                        for k in ("tiny-gpt2", "gpt2", "gpt2-medium"))
    configs = []
    rtt_ms = measure_dispatch_rtt()

    # cfg1: tiny-gpt2, 2-shard, 20 tokens — the notebook workload, timed
    # e2e as mandated. With ~2 dispatches x rtt_ms of tunnel latency in a
    # sub-second workload, this row is RTT-bound by construction; the
    # steady-state row shows what the chip itself does.
    ref_tiny = measure_reference_cpu(tiny, 4, 20)
    pipe_tiny = measure_pipeline(tiny, 2, 4, two_point=False, new_tokens=20)
    tiny_ss = measure_pipeline(tiny, 2, 4, two_point=True)
    configs.append({
        "name": "cfg1_tiny_gpt2_2shard_20tok",
        "tokens_per_sec": round(pipe_tiny["tokens_per_sec"], 2),
        "steady_state_tokens_per_sec": round(tiny_ss["tokens_per_sec"], 2),
        "ref_cpu_tokens_per_sec": round(ref_tiny, 2),
        "vs_baseline": round(pipe_tiny["tokens_per_sec"] / ref_tiny, 2),
        "steady_state_vs_baseline": round(
            tiny_ss["tokens_per_sec"] / ref_tiny, 2),
        "transfer_rtt_ms": round(rtt_ms, 1),
        "note": "2-stage single-program pipeline, " + pipe_tiny["placement"]
                + "; e2e 20-token run pays several fixed tunnel transfers",
    })

    if args.quick:
        print(json.dumps({
            "metric": "greedy_decode_throughput_tiny",
            "value": configs[0]["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": configs[0]["vs_baseline"],
            "configs": configs,
        }))
        return

    # Shared 124M baseline: the reference O(n^2) loop, 20 tokens.
    ref_124 = measure_reference_cpu(g124, PROMPT_LEN, 20)

    # cfg2: 124M single stream — 2-shard pipeline AND the fused
    # single-chip engine (fp32 parity mode + bf16 fast path).
    pipe_124 = measure_pipeline(g124, 2, PROMPT_LEN, 1, "bfloat16")
    eng_f32 = measure_engine(g124, PROMPT_LEN, 1, "float32")
    eng_bf16 = measure_engine(g124, PROMPT_LEN, 1, "bfloat16")
    configs.append({
        "name": "cfg2_gpt2_124m_2shard_single_prompt",
        "tokens_per_sec": round(pipe_124["tokens_per_sec"], 2),
        "engine_fp32_tokens_per_sec": round(eng_f32["tokens_per_sec"], 2),
        "engine_bf16_tokens_per_sec": round(eng_bf16["tokens_per_sec"], 2),
        "p50_token_latency_ms": round(eng_bf16["p50_token_latency_ms"], 3),
        "e2e_tokens_per_sec": round(eng_bf16["e2e_tokens_per_sec"], 2),
        "ref_cpu_tokens_per_sec": round(ref_124, 2),
        "vs_baseline": round(pipe_124["tokens_per_sec"] / ref_124, 2),
        "engine_bf16_vs_baseline": round(
            eng_bf16["tokens_per_sec"] / ref_124, 2),
        "note": "steady-state (marginal) decode rates; 2-stage bf16 "
                "pipeline, " + pipe_124["placement"]
                + "; engine rows are the unstaged single-chip path",
    })

    # cfg3: 124M batch=8. Reference baseline: 8 sequential bs=1 streams ==
    # the same tokens/sec (server.py:137 hardcodes batch 1).
    b8_f32 = measure_engine(g124, PROMPT_LEN, 8, "float32")
    b8_bf16 = measure_engine(g124, PROMPT_LEN, 8, "bfloat16")
    configs.append({
        "name": "cfg3_gpt2_124m_bs8",
        "tokens_per_sec": round(b8_bf16["tokens_per_sec"], 2),
        "engine_fp32_tokens_per_sec": round(b8_f32["tokens_per_sec"], 2),
        "ref_cpu_tokens_per_sec": round(ref_124, 2),
        "vs_baseline": round(b8_bf16["tokens_per_sec"] / ref_124, 2),
        "note": "aggregate steady-state tokens/sec over 8 rows; reference "
                "can only run them sequentially at its bs=1 rate",
    })

    # cfg4: gpt2-medium, 4-shard pipeline.
    ref_med = measure_reference_cpu(gmed, PROMPT_LEN, 10)
    pipe_med = measure_pipeline(gmed, 4, PROMPT_LEN, 1, "bfloat16")
    configs.append({
        "name": "cfg4_gpt2_medium_4shard",
        "tokens_per_sec": round(pipe_med["tokens_per_sec"], 2),
        "ref_cpu_tokens_per_sec": round(ref_med, 2),
        "vs_baseline": round(pipe_med["tokens_per_sec"] / ref_med, 2),
        "placement": pipe_med["placement"],
        "note": "steady-state bf16 4-stage pipeline; baseline is the "
                "reference algorithm on gpt2-medium",
    })

    # cfg5: KV cache vs O(n^2) — both on this framework, same chip, plus
    # the reference CPU loop for scale.
    uncached = measure_uncached_jax(g124, PROMPT_LEN, STEPS_B)
    configs.append({
        "name": "cfg5_kv_cache_vs_on2",
        "tokens_per_sec": round(eng_bf16["tokens_per_sec"], 2),
        "uncached_jax_tokens_per_sec": round(uncached, 2),
        "cache_speedup": round(eng_bf16["tokens_per_sec"] / uncached, 2),
        "ref_cpu_tokens_per_sec": round(ref_124, 2),
        "vs_baseline": round(eng_bf16["tokens_per_sec"] / ref_124, 2),
        "note": "uncached = full fixed-length re-forward per token on-chip "
                "(the reference's algorithm, server.py:169-181), bf16, "
                f"{STEPS_B} tokens",
    })

    print(json.dumps({
        "metric": "greedy_decode_throughput_gpt2_124m",
        "value": configs[1]["engine_bf16_tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": configs[1]["engine_bf16_vs_baseline"],
        "dtype": "bfloat16",
        "fp32_tokens_per_sec": configs[1]["engine_fp32_tokens_per_sec"],
        "transfer_rtt_ms": round(rtt_ms, 1),
        "configs": configs,
    }))


if __name__ == "__main__":
    main()
