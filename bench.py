"""Benchmark harness: the full BASELINE.json measurement matrix.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "configs"}.
The top-level metric is the headline number (GPT-2 124M single-stream greedy
decode, bf16, on the visible TPU chip); ``configs`` carries every
BASELINE.md row so the matrix has measured values instead of TBDs:

  cfg1  tiny-gpt2, 2-shard pipeline, 20 new tokens (the notebook workload)
  cfg2  GPT-2 124M, 2-shard (6+6) + single-chip engine, single prompt
  cfg3  GPT-2 124M, batch=8 (the reference can only run bs=1 sequentially,
        server.py:137 — its baseline is 8x one stream)
  cfg4  GPT-2 medium, 4-shard pipeline (round-robin on this 1 chip: the
        bench environment exposes a single TPU; stage handoffs still run,
        labeled honestly in the row)
  cfg5  KV-cache incremental decode vs O(n^2) full re-forward per token —
        both measured on THIS framework on-chip, plus the reference's own
        O(n^2) torch CPU loop for scale

Baseline denominators re-measure the reference's decode algorithm
in-process on CPU: a torch GPT-2 re-forwarding the FULL growing sequence
per token with no KV cache (reference server.py:169-181), greedy. No
HTTP/JSON hops are charged to it, so every vs_baseline here is
conservative — the deployed reference is slower than its denominator.

Both sides use random-init weights of the same architecture (no HF hub in
this image; throughput is weight-independent). fp32 engine rows exist
because fp32 is the BASELINE.json greedy-parity mode; bf16 rows are the
TPU-native fast path (fp32 LN/softmax/logits, bf16 weights + KV).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

# Fault contract (tools/graftcheck faults pass): the matrix child runs
# under a configured hard timeout; a timeout becomes the row's error
# field, never a hung bench.
FAULT_POLICY = {
    "subprocess.run": ("config", "none",
                       "row records an error on child timeout"),
}

# Timeline contract (tools/graftcheck timeline pass): the
# timeline_overhead row's emit-throughput micro-bench publishes
# occupancy points onto the bus it is measuring.
TIMELINE_EVENTS = {
    "occupancy": "cfg_timeline_overhead micro-bench",
}

PROMPT_LEN = 16
# Two-point decode windows: the bench chip sits behind a network tunnel
# where each host<->device transfer costs ~10-15 ms (measured and reported
# as transfer_rtt_ms) and a generate() call makes several. Timing one
# window charges that fixed cost to the tokens; the marginal cost between
# two windows cancels it, giving the steady-state per-token cost the
# hardware actually delivers.
STEPS_A = 64
STEPS_B = 512


def measure_reference_cpu(config, prompt_len: int, new_tokens: int) -> float:
    """tokens/sec of the reference's O(n^2) CPU decode loop (torch)."""
    import torch
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    torch.manual_seed(0)
    model = GPT2LMHeadModel(HFConfig(
        vocab_size=config.vocab_size, n_positions=config.n_positions,
        n_embd=config.n_embd, n_layer=config.n_layer, n_head=config.n_head))
    model.eval()
    ids = list(np.random.default_rng(0).integers(
        0, config.vocab_size, size=(prompt_len,)))
    # warmup one forward (thread pools, allocator)
    with torch.no_grad():
        model(torch.tensor([ids]))
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        with torch.no_grad():
            logits = model(torch.tensor([ids])).logits[0, -1]
        ids.append(int(torch.argmax(logits)))  # greedy parity mode
    dt = time.perf_counter() - t0
    return new_tokens / dt


def _fetch(out) -> None:
    """Force a REAL device sync by pulling one scalar to the host.

    On the tunneled bench chip ``block_until_ready`` returns before the
    device work finishes (measured: chained 8k matmuls "complete" at
    48 PFLOP/s), so any timing bounded by it records dispatch, not
    compute. A host fetch drains the in-order execution queue for real.
    Every timing window in this file must end with a host fetch (the
    engine/pipeline ``generate`` paths already do, via ``np.asarray`` of
    the token output).
    """
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    idx = (0,) * getattr(leaf, "ndim", 0)
    # slice ON DEVICE before transferring: pulling the full array pays
    # ~1s/6MB over the tunnel and drowns the marginal signal in noise
    np.asarray(leaf[idx] if idx else leaf)


def measure_single_program_e2e(config, prompt_len: int,
                               new_tokens: int) -> dict:
    """The entire generate — prefill + scanned greedy decode — as ONE
    compiled program closed by ONE host fetch: the minimum-sync form of
    the notebook workload (VERDICT r3 next #8). Its wall time is the
    tunnel-RTT floor; anything above it is real device/compile work."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2

    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(
            0, config.vocab_size, size=(1, prompt_len)), jnp.int32)

    @jax.jit
    def full_generate(params, ids):
        cache = gpt2.make_cache(config, 1, prompt_len + new_tokens + 4,
                                jnp.float32)
        logits, cache = gpt2.forward_with_cache(params, ids, config, cache)
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

        def body(carry, _):
            tok, cache = carry
            lg, cache = gpt2.forward_with_cache(params, tok[:, None],
                                                config, cache)
            nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
            return (nxt, cache), nxt

        (_, _), rest = jax.lax.scan(body, (first, cache), None,
                                    length=new_tokens - 1)
        return jnp.concatenate([first, rest[:, 0]])

    _fetch(full_generate(params, prompt))          # compile
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        _fetch(full_generate(params, prompt))
        best = min(best, time.perf_counter() - t0)
    return {"e2e_seconds": best, "tokens_per_sec": new_tokens / best}


def measure_dispatch_rtt() -> float:
    """Fixed per-sync overhead, ms: one host->device->host round trip.

    On the tunneled bench chip each sync barrier costs ~tens of ms
    (measured ~80 ms); a generate() call pays it a couple of times
    (prompt up, tokens down). This fixed cost is what the two-point
    marginal timing cancels."""
    import jax.numpy as jnp

    def roundtrip():
        x = jnp.asarray(np.zeros((1, 256), np.int32))
        _fetch(x + 1)  # +1 defeats any host-side short-circuit

    roundtrip()  # warmup
    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        roundtrip()
    return (time.perf_counter() - t0) / n * 1e3


def marginal_seconds(time_window, n1: int, n2: int, reps: int = 5):
    """THE timing harness for the tunneled backend, used by every config.

    ``time_window(n)`` must run one dependency-chained compiled program of
    size ``n`` closed by a host fetch (see ``_fetch``) and return its wall
    seconds. Two window sizes, min-of-``reps`` each, marginal cost
    ``(t2-t1)/(n2-n1)`` — the fixed ~100 ms sync-barrier cost cancels.
    Returns None when the marginal is non-positive (signal below the
    barrier jitter) rather than reporting nonsense.
    """
    time_window(n1), time_window(n2)               # compile + warm
    t1 = min(time_window(n1) for _ in range(reps))
    t2 = min(time_window(n2) for _ in range(reps))
    m = (t2 - t1) / (n2 - n1)
    return m if m > 0 else None


def _two_point(runner, prompt, s_a: int = STEPS_A, s_b: int = STEPS_B) -> dict:
    """Steady-state decode cost for a ``generate``-style runner."""
    last = {}

    def time_window(n):
        result = runner.generate(prompt, n)
        last[n] = result
        return result.decode_seconds

    marginal = marginal_seconds(time_window, s_a, s_b)
    rb = last[s_b]
    degraded = marginal is None
    if degraded:  # below timer resolution: fall back to the e2e rate
        marginal = rb.decode_seconds / rb.decode_steps
    batch = prompt.shape[0]
    out = {
        "tokens_per_sec": batch / marginal,
        "p50_token_latency_ms": marginal * 1e3,
        "e2e_tokens_per_sec": rb.tokens_per_second,
        "prefill_ms": rb.prefill_seconds * 1e3,
    }
    if degraded:
        out["degraded_timing"] = True
    if rb.verify_steps is not None:  # speculative runner: acceptance stats
        out["verify_steps"] = rb.verify_steps
        out["accepted_tokens_per_verify"] = round(
            rb.new_tokens / rb.verify_steps, 2)
    return out


def measure_engine(config, prompt_len: int, batch: int,
                   dtype_name: str = "float32", s_b: int = STEPS_B,
                   decode_kernel: str = "auto") -> dict:
    """Single-device engine: jitted prefill + scanned KV-cache decode.

    ``dtype_name="int8"`` is the weight-only quantized fast path
    (ops.quant): int8 kernels/embedding, bf16 activations + KV cache.
    ``decode_kernel`` forces a specific attention/stack kernel (the
    crossover rows pin "mega" vs "layer"); "auto" is the production
    dispatch."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import family_module
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
             "int8": "int8"}[dtype_name]
    mod = family_module(config)  # gpt2 or llama geometry, same harness
    params = mod.init_params(config, jax.random.PRNGKey(0))
    engine = DecodeEngine(params, config, max_seq=prompt_len + s_b,
                          dtype=dtype, decode_kernel=decode_kernel)
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, prompt_len))
    return _two_point(engine, prompt, s_b=s_b)


def measure_pipeline(config, n_stages: int, prompt_len: int,
                     batch: int = 1, dtype_name: str = "float32",
                     two_point: bool = True, new_tokens: int = STEPS_A,
                     ) -> dict:
    """N-shard pipelined decode as a single compiled program per phase.

    With >= n_stages real devices this is the shard_map + ppermute decoder
    (one program, stage weights resident per chip, ICI hops). On the 1-chip
    bench environment it falls back to the staged DecodeEngine: the SAME
    validated stage partition (parallel.partition), composed in one
    program on the one chip — labeled in the row. The host-driven
    PipelineRunner is deliberately not timed here: per-token host
    dispatches over the axon tunnel measure RTT, not the framework."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import family_module
    from llm_sharding_demo_tpu.parallel.ppdecode import PipelinedDecoder
    from llm_sharding_demo_tpu.parallel.spmd import make_mesh
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    params = family_module(config).init_params(config, jax.random.PRNGKey(0))
    per = config.n_layer // n_stages
    boundaries = [per * i for i in range(1, n_stages)]
    max_seq = prompt_len + (STEPS_B if two_point else new_tokens)
    n_real = len(jax.devices())
    if n_real >= n_stages:
        mesh = make_mesh({"pp": n_stages}, jax.devices()[:n_stages])
        runner = PipelinedDecoder(params, config, mesh, max_seq=max_seq,
                                  dtype=dtype)
        placement = f"ppermute over {n_stages} devices"
    else:
        runner = DecodeEngine(params, config, max_seq=max_seq, dtype=dtype,
                              boundaries=boundaries)
        placement = f"{n_stages} stages fused on {n_real} chip(s)"
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, prompt_len))
    if two_point:
        out = _two_point(runner, prompt)
    else:  # fixed workload (cfg1's mandated 20 tokens): e2e, RTT included
        runner.generate(prompt, new_tokens)        # warmup
        result = runner.generate(prompt, new_tokens)
        out = {
            "tokens_per_sec": result.tokens_per_second,
            "p50_token_latency_ms": result.per_token_latency * 1e3,
        }
    out["placement"] = placement
    return out


def measure_moe(prompt_len: int, batch: int = 1,
                dtype_name: str = "bfloat16", config=None) -> dict:
    """MoE decode: GPT-2-124M geometry with the MLP swapped for 8 experts
    (top-2, ~7x the MLP weights). Exercises the second model family's
    cached decode path end-to-end on-chip."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import moe
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
             "int8": "int8"}[dtype_name]
    if config is None:
        config = moe.MoEConfig(vocab_size=50257, n_positions=1024, n_embd=768,
                               n_layer=12, n_head=12, n_experts=8,
                               expert_top_k=2)
    params = moe.init_params(config, jax.random.PRNGKey(0))
    engine = DecodeEngine(params, config, max_seq=prompt_len + STEPS_B,
                          dtype=dtype)
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, prompt_len))
    return _two_point(engine, prompt)


def measure_spec_decode(config, prompt_len: int,
                        dtype_name: str = "bfloat16", draft_len: int = 6,
                        s_b: int = STEPS_B) -> dict:
    """Prompt-lookup speculative decode vs the plain engine, same weights.

    Greedy speculation is token-exact (runtime.spec_decode), so this is a
    pure latency measurement: tokens/sec of the verify-loop program vs the
    one-token-per-forward scan, plus the realized acceptance (tokens per
    verify forward). Greedy decode from a random prompt settles into a
    repetition loop — the favorable case for lookup drafting; the row
    reports acceptance so the speedup can be read in context (worst case,
    zero acceptance, speculation degrades toward the K+1-token forward
    cost per token)."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import family_module
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.spec_decode import SpecDecodeEngine

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
             "int8": "int8"}[dtype_name]
    params = family_module(config).init_params(config, jax.random.PRNGKey(0))
    max_seq = min(prompt_len + s_b + draft_len, config.n_positions)
    spec = SpecDecodeEngine(params, config, max_seq=max_seq, dtype=dtype,
                            draft_len=draft_len)
    plain = DecodeEngine(params, config, max_seq=max_seq, dtype=dtype)
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(1, prompt_len))

    spec_out = _two_point(spec, prompt, s_b=s_b)      # shared harness:
    plain_out = _two_point(plain, prompt, s_b=s_b)    # degraded fallback etc.
    out = {
        "spec_tokens_per_sec": spec_out["tokens_per_sec"],
        "plain_tokens_per_sec": plain_out["tokens_per_sec"],
        "verify_steps": spec_out["verify_steps"],
        "accepted_tokens_per_verify": spec_out["accepted_tokens_per_verify"],
        "draft_len": draft_len,
        "speedup": round(
            spec_out["tokens_per_sec"] / plain_out["tokens_per_sec"], 2),
    }
    if spec_out.get("degraded_timing") or plain_out.get("degraded_timing"):
        out["degraded_timing"] = True
    return out


def measure_flash_attention(seq_lens=(1024, 2048, 4096), iters: int = 0,
                            ) -> list:
    """Pallas flash kernel vs the XLA einsum attention, fwd and fwd+bwd.

    GPT-2 124M head geometry (H=12, hd=64), bf16 inputs, per-S speedups.
    Run on whatever backend is visible; on CPU the kernel drops to
    interpret mode, so only the TPU numbers are meaningful (rows carry the
    backend name). ``iters=0`` picks a per-S window sized so the marginal
    signal clears the tunnel's ~100ms sync-barrier jitter; a marginal that
    still comes out non-positive is reported as null (below resolution),
    never as a negative "speedup".
    """
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.ops.attention import causal_attention
    from llm_sharding_demo_tpu.ops.flash_attention import flash_attention

    interpret = jax.default_backend() != "tpu"
    if interpret:
        # interpret mode runs the kernel grid in Python — thousands of
        # chained calls would take hours and the numbers are meaningless
        # anyway (the docstring's caveat); report the skip instead.
        return [{"seq_len": s, "skipped": "non-TPU backend (interpret "
                 "mode); kernel timings are TPU-only",
                 "backend": jax.default_backend()} for s in seq_lens]
    rows = []
    for s in seq_lens:
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, 12, s, 64),
                                     dtype=jnp.bfloat16) for i in range(3))

        def flash_fwd(q, k, v):
            return flash_attention(q, k, v, interpret=interpret)

        def _chain_grads(fwd, q, k, v):
            # all three grads feed the carry (else XLA DCEs the dk/dv
            # kernels); normalized so 100+ chained steps stay finite
            dq, dk, dv = jax.grad(
                lambda q, k, v: fwd(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2))(q, k, v)
            acc = (dq + dk + dv).astype(jnp.float32)
            return (acc / jnp.maximum(jnp.max(jnp.abs(acc)), 1e-3)
                    ).astype(q.dtype)

        def flash_step(q, k, v):
            return _chain_grads(flash_fwd, q, k, v)

        def xla_step(q, k, v):
            return _chain_grads(causal_attention, q, k, v)

        def time_it(op, n_iters):
            # N dependency-chained invocations inside ONE program (the
            # output feeds the next call's q), closed by a host fetch:
            # on the tunneled backend independent dispatches can't be
            # trusted to serialize, and block_until_ready is not a sync
            # barrier (see _fetch) — dataflow chaining is.
            compiled = {}

            def make(n):
                if n not in compiled:
                    @jax.jit
                    def run(q, k, v):
                        return jax.lax.fori_loop(
                            0, n, lambda i, acc: op(acc, k, v), q)
                    compiled[n] = run
                return compiled[n]

            def time_window(n):
                fn = make(n)
                t0 = time.perf_counter()
                _fetch(fn(q, k, v))
                return time.perf_counter() - t0

            m = marginal_seconds(time_window, n_iters, 5 * n_iters)
            return None if m is None else m * 1e3

        # window sized inversely to the O(S^2) op cost so the marginal
        # signal stays well above barrier jitter at every S
        n = iters or max(25, int(400 * (1024 / s) ** 2))
        t_flash, t_xla = time_it(flash_fwd, n), time_it(causal_attention, n)
        tb_flash, tb_xla = time_it(flash_step, n), time_it(xla_step, n)

        def rnd(x):
            return None if x is None else round(x, 3)

        def ratio(a, b):
            return None if (a is None or b is None) else round(a / b, 2)

        from llm_sharding_demo_tpu.ops.flash_attention import flash_profitable
        auto = "pallas" if flash_profitable(s) else "xla"
        rows.append({
            "seq_len": s,
            "fwd_flash_ms": rnd(t_flash),
            "fwd_xla_ms": rnd(t_xla),
            "fwd_speedup": ratio(t_xla, t_flash),
            "fwdbwd_flash_ms": rnd(tb_flash),
            "fwdbwd_xla_ms": rnd(tb_xla),
            "fwdbwd_speedup": ratio(tb_xla, tb_flash),
            # what attention_impl="pallas" actually runs at this length:
            # dispatch-by-measured-crossover (ops.flash_attention.
            # flash_profitable), so the effective speedup is
            # max(1.0, kernel speedup) — the kernel never regresses
            "auto_dispatch": auto,
            "backend": jax.default_backend(),
        })
    return rows


def measure_uncached_jax(config, prompt_len: int, new_tokens: int,
                         dtype_name: str = "bfloat16",
                         n1: int = STEPS_A):
    """Our model WITHOUT the KV cache: re-forward the full fixed-length
    sequence per token (one compile; the reference's O(n^2) algorithm at
    constant shape). Denominator for cfg5's cache-speedup ratio. The
    per-token host dispatches pipeline asynchronously, so tunnel RTT is
    naturally hidden here — comparable with the cached steady-state."""
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    total = prompt_len + new_tokens

    def step(ids, t):
        logits = gpt2.forward(params, ids, config)          # [1, total, V]
        nxt = jnp.argmax(jax.lax.dynamic_slice(
            logits, (0, t - 1, 0), (1, 1, config.vocab_size))).astype(jnp.int32)
        return jax.lax.dynamic_update_slice(ids, nxt[None, None], (0, t))

    def make(n_tokens: int):
        # the whole n-token O(n^2) decode as ONE chained program — each
        # step's ids feed the next, so device time is dataflow-serialized
        # and the closing host fetch (_fetch) bounds it honestly
        @jax.jit
        def run(ids):
            return jax.lax.fori_loop(
                prompt_len, prompt_len + n_tokens,
                lambda t, ids: step(ids, t), ids)
        return run

    ids0 = np.zeros((1, total), dtype=np.int32)
    ids0[0, :prompt_len] = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(prompt_len,))
    ids0 = jnp.asarray(ids0)
    compiled = {}

    def time_window(n) -> float:
        if n not in compiled:
            compiled[n] = make(n)
        t0 = time.perf_counter()
        _fetch(compiled[n](ids0))
        return time.perf_counter() - t0

    # marginal rate over tokens [n1, new_tokens) — ``n1`` defaults to the
    # SAME small window the cached engine's two-point marginal starts at,
    # so cfg5's cached/uncached rates cover identical token ranges (the
    # uncached path is O(n^2): a deeper-only window would understate its
    # rate and overstate the cache speedup). None when below resolution.
    m = marginal_seconds(time_window, n1, new_tokens)
    return None if m is None else 1.0 / m


FULL_MATRIX_FILE = "BENCH_full.json"
_COMPACT_DROP = ("note", "traceback_tail", "metrics_delta")


def _metrics_delta(before: dict, after: dict, limit: int = 60) -> dict:
    """Changed series between two ``REGISTRY.snapshot()`` calls, per
    bench config row: counters/histograms as deltas, gauges at their
    final value. Journaled alongside each row's timing so acceptance
    rates, cache hits, and compile events per config become part of the
    perf trajectory instead of being lost when the process exits. Kept
    out of the compact driver line (``_COMPACT_DROP``) — the full
    matrix file and the progress journal carry it."""
    from llm_sharding_demo_tpu.utils.metrics import METRIC_CATALOG
    changed = {}
    for k, v in sorted(after.items()):
        if not isinstance(v, (int, float)) or k.endswith("_avg"):
            continue
        base = k.split("{", 1)[0]
        if METRIC_CATALOG.get(base) == "gauge":
            if before.get(k) != v:
                changed[k] = v
        else:
            d = v - before.get(k, 0)
            if d:
                changed[k] = round(d, 6)
    if len(changed) <= limit:  # exactly-limit rows must not claim truncation
        return changed
    out = dict(list(changed.items())[:limit])
    out["truncated"] = True
    return out


def emit(payload: dict, write_file: bool = True) -> None:
    """Write the FULL annotated matrix to ``FULL_MATRIX_FILE`` and print a
    COMPACT single JSON line for the driver's tail capture.

    Round 2 lost half its measurement matrix: the one output line (nine
    configs with long prose notes) outgrew the driver's tail window and
    BENCH_r02.json recorded ``parsed: null`` (VERDICT.md missing #1). The
    driver contract is one parseable line; the prose belongs in the
    committed file. ``write_file=False`` (--quick smoke runs) keeps a
    full run's committed matrix from being clobbered by a one-config
    smoke payload.
    """
    import os
    if write_file:
        here = os.path.dirname(os.path.abspath(__file__))
        full_path = os.path.join(here, FULL_MATRIX_FILE)
        try:
            with open(full_path, "w") as f:
                json.dump(payload, f, indent=2)
                f.write("\n")
        except OSError:
            pass  # read-only checkout: the compact line still reports
        try:
            # BASELINE.md's measured table is RENDERED from this artifact
            # (VERDICT r4 weak #7: regenerate, don't accrete)
            sys_path_added = False
            import sys as _sys
            tools = os.path.join(here, "tools")
            if tools not in _sys.path:
                _sys.path.insert(0, tools)
                sys_path_added = True
            import render_baseline
            render_baseline.update_file(os.path.join(here, "BASELINE.md"),
                                        payload)
            if sys_path_added:
                _sys.path.remove(tools)
        except Exception:  # noqa: BLE001 — rendering must never cost the
            pass           # artifact its JSON line

    def compact_cfg(cfg: dict) -> dict:
        out = {}
        for k, v in cfg.items():
            if k in _COMPACT_DROP:
                continue
            if isinstance(v, str) and len(v) > 80:
                v = v[:77] + "..."
            out[k] = v
        return out

    compact = {k: v for k, v in payload.items() if k != "configs"}
    compact["configs"] = [compact_cfg(c) for c in payload.get("configs", [])]
    if write_file:
        compact["full_matrix_file"] = FULL_MATRIX_FILE
    print(json.dumps(compact))


def measure_iterbatch(config, dtype="bfloat16", n_requests: int = 12,
                      max_batch: int = 4, steps: int = 192,
                      prompt_len: int = 60, stagger_s: float = 0.04,
                      seg_steps: int = 64) -> dict:
    """Staggered-arrival serving throughput: the admission batcher
    (rounds run to completion) vs the iteration-level scheduler
    (requests join the live batch at segment boundaries) on the same
    weights and workload. Arrivals are staggered so most requests land
    MID-decode — the case admission-level batching serializes.

    Wall-clock aggregate includes every host sync either scheduler pays
    (on the tunneled bench chip a sync is ~100 ms, so this is an honest
    end-to-end number, not a device-only one). All requests share one
    shape, so each scheduler compiles a bounded handful of programs.
    """
    import threading as _th

    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.batcher import BatchingEngine
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine

    params = gpt2.init_params(config, jax.random.PRNGKey(0),
                              dtype=jnp.float32)
    # cache headroom beyond one generation: a mid-decode joiner needs
    # depth + its steps to fit, so without headroom nothing ever joins
    # (the uniform-depth design spends d - plen slots on a late joiner)
    bucketed = (prompt_len + 15) // 16 * 16
    max_seq = min(config.n_positions, bucketed + 4 * steps)
    engine = DecodeEngine(params, config, max_seq=max_seq, dtype=dtype)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, config.vocab_size, size=(prompt_len,))

    def drive(sched) -> float:
        done = [None] * n_requests

        def run(i):
            time.sleep(i * stagger_s)
            done[i] = sched.generate(prompt, steps)

        t0 = time.perf_counter()
        threads = [_th.Thread(target=run, args=(i,))
                   for i in range(n_requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        assert all(r is not None for r in done)
        return n_requests * steps / dt

    results = {}
    for name, make in (
            ("admission", lambda: BatchingEngine(
                engine, max_batch=max_batch, max_wait_ms=5.0)),
            ("iter", lambda: IterBatchingEngine(
                engine, max_batch=max_batch, seg_steps=seg_steps,
                max_wait_ms=5.0))):
        sched = make()
        drive(sched)                 # warmup: compiles + caches programs
        before = sched.stats() if name == "iter" else None
        results[name] = drive(sched)
        if name == "iter":
            after = sched.stats()    # delta = the measured drive only
            results["iter_stats"] = {
                k: after[k] - before[k] for k in after}
    return {
        "admission_tokens_per_sec": round(results["admission"], 1),
        "iter_tokens_per_sec": round(results["iter"], 1),
        "iter_vs_admission": round(results["iter"] / results["admission"],
                                   2),
        "n_requests": n_requests, "max_batch": max_batch, "steps": steps,
        "stagger_ms": round(stagger_s * 1e3, 1),
        "seg_steps": seg_steps,
        "iter_joins": results["iter_stats"]["joins"],
        "iter_segments": results["iter_stats"]["segments"],
    }


def measure_paged_kv(config, dtype="bfloat16", steps: int = 192,
                     prompt_len: int = 60, block_size: int = 16,
                     max_batch: int = 8) -> dict:
    """Paged vs contiguous decode (ISSUE 5): (a) solo decode rate
    through the PagedKVRunner (the engine's own programs + one
    gather/scatter round trip per segment) vs the plain engine — the
    paging tax; (b) max concurrent iterbatch rows before the first
    preemption on a deliberately small pool — the capacity the block
    granularity buys over per-row max_seq arenas.

    Needs the bench chip: CPU rates for the gather/scatter overhead
    would mislead (the tax is HBM traffic, not host arithmetic).
    """
    import threading as _th

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {"skipped": "paged-vs-contiguous rates need the bench "
                           "chip (the paging tax is HBM traffic; CPU "
                           "numbers would mislead)"}

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import (KVBlockPool,
                                                       PagedKVRunner)

    params = gpt2.init_params(config, jax.random.PRNGKey(0),
                              dtype=jnp.float32)
    bucketed = (prompt_len + 15) // 16 * 16
    max_seq = min(config.n_positions,
                  -(-(bucketed + 2 * steps) // block_size) * block_size)
    engine = DecodeEngine(params, config, max_seq=max_seq, dtype=dtype)
    nbm = max_seq // block_size
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, config.vocab_size, size=(prompt_len,))

    # (a) solo paged vs contiguous decode rate
    pool = KVBlockPool.for_engine(engine, num_blocks=2 * nbm,
                                  block_size=block_size)
    runner = PagedKVRunner(engine, pool)
    engine.generate(prompt[None, :], steps)          # warmup/compile
    runner.generate(prompt[None, :], steps)
    t0 = time.perf_counter()
    contiguous = engine.generate(prompt[None, :], steps)
    t1 = time.perf_counter()
    runner.generate(prompt[None, :], steps)
    t2 = time.perf_counter()
    contig_rate = steps / (t1 - t0)
    paged_rate = steps / (t2 - t1)

    # (b) concurrency before first preemption: a pool of 2 full rows'
    # worth of blocks, rows that each need ~1/2 row — block granularity
    # admits ~4 before pressure; the contiguous allocator would cap at
    # pool_bytes / max_seq_row = 2
    small = KVBlockPool.for_engine(engine, num_blocks=2 * nbm,
                                   block_size=block_size, watermark=1.0)
    ib = IterBatchingEngine(engine, max_batch=max_batch, seg_steps=64,
                            max_wait_ms=200.0, pool=small)
    admitted = 0
    threads = []

    def run_one():
        ib.generate(prompt, steps, timeout=600)

    for i in range(max_batch):
        if ib.stats()["preemptions"] > 0:
            break
        threads.append(_th.Thread(target=run_one))
        threads[-1].start()
        admitted += 1
        time.sleep(0.2)
    for t in threads:
        t.join()
    st = ib.stats()
    return {
        "contiguous_tokens_per_sec": round(contig_rate, 1),
        "paged_tokens_per_sec": round(paged_rate, 1),
        "paging_tax": round(1 - paged_rate / contig_rate, 3),
        "block_size": block_size, "max_seq": max_seq,
        "pool_blocks": 2 * nbm,
        "rows_admitted_before_first_preemption": admitted,
        "contiguous_rows_that_pool_could_hold": 2,
        "preemptions": st["preemptions"], "resumes": st["resumes"],
    }


def measure_kv_quant_capacity(config, steps: int = 192,
                              prompt_len: int = 60, block_size: int = 16,
                              max_batch: int = 12) -> dict:
    """Quantized-vs-f32 KV capacity at EQUAL pool bytes (ISSUE 16): two
    pools sized to the same HBM budget — the f32 pool's byte footprint,
    with the int8 pool taking however many narrow blocks fit in those
    bytes (``kv_pool.bytes_per_block`` arithmetic, scales included) —
    driven through the iteration scheduler until the first preemption.
    The admitted-row ratio IS the effective-capacity claim: admission is
    denominated in blocks, so narrow storage converts to concurrency
    with zero scheduler changes. Also journals each pool's prefix-store
    depth (whole aligned prompts the allocator can hold resident — the
    same blocks_for arithmetic the prefix store's LRU lives under).
    The kv.int8 accuracy side of the trade rides the numerics_oracle
    row (kv_int8_logit_mse / kv_int8_top1_agreement), gated by
    bench_diff alongside this row's capacity metrics.

    Needs the bench chip: the 2-4x is HBM bytes; host-RAM pools would
    journal a vacuous ratio.
    """
    import threading as _th

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {"skipped": "kv-quant capacity needs the bench chip "
                           "(the claimed 2-4x is HBM bytes; host-RAM "
                           "pools would journal a vacuous ratio)"}

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import (KVBlockPool,
                                                       bytes_per_block)

    params = gpt2.init_params(config, jax.random.PRNGKey(0),
                              dtype=jnp.float32)
    bucketed = (prompt_len + 15) // 16 * 16
    max_seq = min(config.n_positions,
                  -(-(bucketed + 2 * steps) // block_size) * block_size)
    # f32 engine: the full-precision pool inherits 4-byte blocks, so the
    # equal-byte comparison is the paper-claim shape (int8 vs f32)
    engine = DecodeEngine(params, config, max_seq=max_seq, dtype="float32")
    nbm = max_seq // block_size
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, config.vocab_size, size=(prompt_len,))

    full_bpb = bytes_per_block(config.n_layer, config.n_head, block_size,
                               config.head_dim, dtype=jnp.float32)
    int8_bpb = bytes_per_block(config.n_layer, config.n_head, block_size,
                               config.head_dim, dtype=jnp.float32,
                               block_dtype="int8")
    full_blocks = 2 * nbm                   # two full rows' worth
    budget = full_blocks * full_bpb
    int8_blocks = budget // int8_bpb

    def rows_before_preemption(pool):
        ib = IterBatchingEngine(engine, max_batch=max_batch, seg_steps=64,
                                max_wait_ms=200.0, pool=pool)
        admitted = 0
        threads = []

        def run_one():
            ib.generate(prompt, steps, timeout=600)

        for _ in range(max_batch):
            if ib.stats()["preemptions"] > 0:
                break
            threads.append(_th.Thread(target=run_one))
            threads[-1].start()
            admitted += 1
            time.sleep(0.2)
        for t in threads:
            t.join()
        # whole aligned prompts resident at once = the prefix store's
        # depth bound on this pool (its entries hold these same blocks)
        depth = (pool.allocator.num_blocks
                 // pool.allocator.blocks_for(bucketed))
        return admitted, depth, ib.stats()

    f32_pool = KVBlockPool.for_engine(engine, num_blocks=full_blocks,
                                      block_size=block_size, watermark=1.0)
    f32_rows, f32_depth, f32_st = rows_before_preemption(f32_pool)
    q_pool = KVBlockPool.for_engine(engine, num_blocks=int(int8_blocks),
                                    block_size=block_size, watermark=1.0,
                                    block_dtype="int8")
    q_rows, q_depth, q_st = rows_before_preemption(q_pool)
    return {
        "pool_bytes": int(budget),
        "f32_bytes_per_block": int(full_bpb),
        "int8_bytes_per_block": int(int8_bpb),
        "f32_pool_blocks": int(full_blocks),
        "int8_pool_blocks": int(int8_blocks),
        "f32_before_first_preemption": f32_rows,
        "int8_before_first_preemption": q_rows,
        "capacity_ratio": round(q_rows / max(f32_rows, 1), 2),
        "f32_prefix_store_depth": f32_depth,
        "int8_prefix_store_depth": q_depth,
        "f32_preemptions": f32_st["preemptions"],
        "int8_preemptions": q_st["preemptions"],
    }


def measure_tiered_kv_depth(n_requests: int = 56, prefix_depth: int = 24,
                            seed: int = 5, max_new: int = 8,
                            block_size: int = 8,
                            device_blocks: int = 16) -> dict:
    """grafttier capacity row (ISSUE 20): a bursty_chat-derived prefix
    population (the loadgen ``prefix_depth`` knob) driven through a
    deliberately small device pool with a host-RAM spill tier attached
    (``runtime.kv_tier``), twice over the SAME seeded schedule. The
    cold epoch inserts every arrival's full-depth prefix entry and the
    store's capacity trim demotes them to the host tier; the warm
    epoch replays the identical arrivals, so every lookup lands on a
    demoted entry and promotes it back — the affinity-hit path.

    The capacity claim is LEDGER-MEASURED, never shape arithmetic:
    ``depth_ratio`` divides the host tier's resident bytes (graftmem
    ``host_spill`` holding, the same single bookkeeping path
    /debug/memory serves) by the device pool's plane bytes (codes +
    scales holdings) at the cold epoch's end — the >= 10x prefix-store
    depth the tier buys over the device pool alone. The warm epoch
    contributes the serving-side rates: prefix/promoted hit rates and
    goodput (higher-better), mean promote stall (lower-better), all
    gated by tools/bench_diff.py.

    Runs on any backend: the depth claim is byte accounting and the
    rates are within-row (one epoch vs its own wall), not chip rates.
    """
    import dataclasses as _dc

    import jax

    from llm_sharding_demo_tpu.loadgen.profiles import PROFILES
    from llm_sharding_demo_tpu.loadgen.schedule import schedule
    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import (KVBlockPool,
                                                       PagedKVRunner)
    from llm_sharding_demo_tpu.runtime.kv_tier import HostKVTier
    from llm_sharding_demo_tpu.runtime.prefix_cache import \
        PrefixCachingEngine
    from llm_sharding_demo_tpu.utils import graftmem

    # byte-vocab micro model: arrival prompt STRINGS encode directly to
    # token ids, so the driven prefixes are exactly the profile's
    # deterministic shared_prefix population
    config = gpt2.GPT2Config(vocab_size=256, n_positions=128, n_embd=32,
                             n_layer=2, n_head=4)
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    engine = DecodeEngine(params, config, max_seq=96)
    pool = KVBlockPool.for_engine(engine, num_blocks=device_blocks,
                                  block_size=block_size)
    host_blocks = 16 * device_blocks
    pool.attach_tier(HostKVTier(host_blocks))
    # capacity=2 keeps at most two entries device-resident — every
    # further insert demotes through the tier ladder, which is the
    # whole point of the row
    pref = PrefixCachingEngine(engine, capacity=2, chunk=block_size,
                               pool=pool)
    runner = PagedKVRunner(engine, pool, prefix=pref)

    prof = _dc.replace(PROFILES["bursty_chat"], prefix_depth=prefix_depth)
    arrivals = schedule(prof, seed, n_requests)
    prompts = [np.frombuffer(a.prompt.encode("utf-8"),
                             dtype=np.uint8).astype(np.int32)[:80]
               for a in arrivals]

    def epoch() -> float:
        t0 = time.perf_counter()
        for p in prompts:
            runner.generate(p, max_new)
        return time.perf_counter() - t0

    cold_s = epoch()                       # insert + demote (and XLA
    #                                        compiles — warm excludes)
    pool_bytes = (graftmem.holding_bytes(pool, "data")
                  + graftmem.holding_bytes(pool, "scales"))
    cold_tier = pool.tier.stats()
    cold_store = pref.stats()
    warm_s = epoch()                       # replay: promote on hit
    warm_tier = pool.tier.stats()
    warm_store = pref.stats()
    hits = warm_store["hits"] - cold_store["hits"]
    promoted = warm_tier["promotions"] - cold_tier["promotions"]
    stall_ms = (warm_tier["promote_ms_total"]
                - cold_tier["promote_ms_total"])
    return {
        "requests_per_epoch": n_requests,
        "prefix_depth": prefix_depth,
        "seed": seed,
        "device_pool_bytes": int(pool_bytes),
        "host_bytes_resident": int(cold_tier["host_bytes"]),
        "host_blocks_in_use": cold_tier["host_blocks_in_use"],
        "host_blocks_total": host_blocks,
        "depth_ratio": round(cold_tier["host_bytes"]
                             / max(pool_bytes, 1), 2),
        "demotions": warm_tier["demotions"],
        "discards": warm_tier["discards"],
        "prefix_hit_rate": round(hits / max(n_requests, 1), 3),
        "promoted_hit_rate": round(promoted / max(n_requests, 1), 3),
        "goodput_rps": round(n_requests / max(warm_s, 1e-9), 2),
        "promote_stall_ms": round(stall_ms / max(promoted, 1), 3),
        "cold_epoch_s": round(cold_s, 3),
        "warm_epoch_s": round(warm_s, 3),
    }


def measure_concurrent_load(config, dtype="bfloat16", width: int = 6,
                            steps: int = 96, prompt_len: int = 48,
                            block_size: int = 16) -> dict:
    """Concurrent-load latency + lock-contention row (ISSUE 8): ``width``
    (>= 4) simultaneous clients through the pooled iteration scheduler,
    with every declared lock constructed as an instrumented graftsched
    ``TracedLock`` in accounting-only mode (``GRAFTSCHED=trace``: wait
    totals, no schedule perturbation). Journals per-request p50/p99
    latency AND the per-lock contention totals — so a change that makes
    the host-side scheduler serialize on a blocked lock (exactly the
    stall TokenWeave-style overlap cannot absorb, ROADMAP item 3) shows
    up in the same trajectory as the latencies it causes.

    Needs the bench chip: CPU decode rates make queueing, not locking,
    the bottleneck, and the contention split would mislead.
    """
    import threading as _th

    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "concurrent-load lock contention needs the "
                           "bench chip (on CPU the decode itself "
                           "dominates and the wait split is noise)"}

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import KVBlockPool
    from llm_sharding_demo_tpu.utils import graftsched

    from llm_sharding_demo_tpu.utils import metrics as _metrics
    from llm_sharding_demo_tpu.utils import tracing as _tracing

    prior = os.environ.get("GRAFTSCHED")
    os.environ["GRAFTSCHED"] = "trace"    # accounting only, no yields
    # the module-singleton registry/recorder locks were constructed at
    # import time (before the env was armed) — re-wrap them so their
    # contention is measured too. Safe here: this row runs before its
    # own threads start, and prior rows' worker threads are idle in
    # queue.get (no REGISTRY call in flight).
    reg_lock, rec_lock = _metrics.REGISTRY._lock, _tracing.RECORDER._lock
    _metrics.REGISTRY._lock = graftsched.lock(
        "metrics.MetricsRegistry._lock")
    _tracing.RECORDER._lock = graftsched.lock(
        "tracing.FlightRecorder._lock")
    try:
        graftsched.clear()
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
        bucketed = (prompt_len + 15) // 16 * 16
        max_seq = min(config.n_positions, bucketed + 2 * steps)
        engine = DecodeEngine(params, config, max_seq=max_seq,
                              dtype=dtype)
        nbm = -(-max_seq // block_size)
        pool = KVBlockPool.for_engine(engine, num_blocks=width * nbm,
                                      block_size=block_size)
        ib = IterBatchingEngine(engine, max_batch=width, seg_steps=32,
                                max_wait_ms=20.0, pool=pool)
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, config.vocab_size, size=(prompt_len,))
        ib.generate(prompt, steps, timeout=600)       # warmup/compile

        lat = [0.0] * width

        def run_one(i):
            t0 = time.perf_counter()
            ib.generate(prompt, steps, timeout=600)
            lat[i] = time.perf_counter() - t0

        graftsched.clear()                # contention for the run only
        threads = [_th.Thread(target=run_one, args=(i,))
                   for i in range(width)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        cont = graftsched.contention()
        return {
            "width": width,
            "steps_per_request": steps,
            "p50_request_latency_ms": round(
                float(np.percentile(lat, 50)) * 1e3, 1),
            "p99_request_latency_ms": round(
                float(np.percentile(lat, 99)) * 1e3, 1),
            "aggregate_tokens_per_sec": round(width * steps / wall, 1),
            "lock_contention": cont,
            "lock_wait_total_ms": round(
                sum(v["wait_seconds"] for v in cont.values()) * 1e3, 2),
            "findings": [f.format() for f in graftsched.findings()],
        }
    finally:
        _metrics.REGISTRY._lock = reg_lock
        _tracing.RECORDER._lock = rec_lock
        if prior is None:
            os.environ.pop("GRAFTSCHED", None)
        else:
            os.environ["GRAFTSCHED"] = prior


def measure_fault_recovery(config, dtype="bfloat16", width: int = 6,
                           steps: int = 96, prompt_len: int = 48,
                           block_size: int = 16, fault_rate: float = 0.10,
                           fault_seed: int = 10) -> dict:
    """Degraded-mode serving cost row (ISSUE 10, graftfault): ``width``
    concurrent clients through the pooled iteration scheduler with a
    PINNED seeded fault plan injecting transient decode faults at
    ``fault_rate`` per segment — every faulted segment parks the live
    rows through the recompute-resume path and replays them
    byte-identically. Journals p50/p99 request latency, the success
    rate, and the park/resume counts, so the price of fault recovery
    rides the same trajectory (tools/bench_diff.py gates success_rate
    higher-better and the latencies lower-better) as the fast path.

    Needs the bench chip for the same reason concurrent_load does: CPU
    decode rates make queueing, not recovery, the bottleneck.
    """
    import threading as _th

    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "fault-recovery latency needs the bench "
                           "chip (on CPU the decode itself dominates "
                           "and the recovery tax is noise)"}

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import KVBlockPool
    from llm_sharding_demo_tpu.utils import graftfault

    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    bucketed = (prompt_len + 15) // 16 * 16
    max_seq = min(config.n_positions, bucketed + 2 * steps)
    engine = DecodeEngine(params, config, max_seq=max_seq, dtype=dtype)
    nbm = -(-max_seq // block_size)
    pool = KVBlockPool.for_engine(engine, num_blocks=width * nbm,
                                  block_size=block_size)
    ib = IterBatchingEngine(engine, max_batch=width, seg_steps=32,
                            max_wait_ms=20.0, pool=pool)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, config.vocab_size, size=(prompt_len,))
    ib.generate(prompt, steps, timeout=600)       # warmup/compile

    lat = [0.0] * width
    ok = [False] * width

    def run_one(i):
        t0 = time.perf_counter()
        try:
            ib.generate(prompt, steps, timeout=600)
            ok[i] = True
        except Exception:  # noqa: BLE001 — failure IS the measurement
            pass
        lat[i] = time.perf_counter() - t0

    plan = graftfault.FaultPlan(seed=fault_seed, rate=fault_rate,
                                sites={"iterbatch.decode_seg"},
                                kinds={"decode_transient"})
    base = ib.stats()
    with graftfault.use(plan):
        threads = [_th.Thread(target=run_one, args=(i,))
                   for i in range(width)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    st = ib.stats()
    return {
        "width": width,
        "steps_per_request": steps,
        "fault_rate": fault_rate,
        "fault_seed": fault_seed,
        "injected_faults": len(plan.injections),
        "fault_parks": st["fault_parks"] - base["fault_parks"],
        "resumes": st["resumes"] - base["resumes"],
        "success_rate": round(sum(ok) / width, 4),
        "p50_request_latency_ms": round(
            float(np.percentile(lat, 50)) * 1e3, 1),
        "p99_request_latency_ms": round(
            float(np.percentile(lat, 99)) * 1e3, 1),
        "aggregate_tokens_per_sec": round(width * steps / wall, 1),
    }


def measure_graftload(profiles=("bursty_chat", "agentic"), seed: int = 0,
                      n_requests: int = 16,
                      rate_scales=(1.0, 2.0)) -> dict:
    """graftload rows (ISSUE 11): the seeded open-loop scenario harness
    driven against the in-process pooled-iterbatch serving app —
    ``rate_scales`` sweeps each profile's declared arrival rate, so
    every (profile, rate) pair contributes one throughput-vs-p99
    Pareto point, and the base rate contributes the per-profile
    goodput/SLO-attainment row (typed 429/503 sheds counted separately
    from SLO misses). The schedule is a pure function of (seed,
    profile, k) — this row replays identically run to run.

    Needs the bench chip: on CPU the decode itself dominates and the
    Pareto front would measure the host, not the serving stack.
    """
    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "open-loop load rates need the bench chip "
                           "(on CPU the decode itself dominates and "
                           "the Pareto front would measure the host, "
                           "not the serving stack)"}

    from llm_sharding_demo_tpu import loadgen
    from llm_sharding_demo_tpu.utils import graftscope
    from tools.graftload import build_demo_app

    client, recorder, _registry = build_demo_app(
        max_seq=256, max_batch=4,
        recorder_capacity=max(64, 2 * n_requests * len(profiles)
                              * len(rate_scales)))
    # warmup/compile pass (serial, tiny): the open-loop tails must
    # measure serving, not first-touch XLA compiles
    loadgen.run_load(client, loadgen.profile(profiles[0]),
                     seed=seed + 1, n=2, mode="serial",
                     recorder=recorder)
    # window the journaled occupancy to the sweep itself — the
    # graftscope rings are process-global and earlier bench configs
    # (concurrent_load, fault_recovery) sampled the same series
    occ_since = graftscope.now_ms()
    pareto, slo_rows, reports = [], [], []
    for name in profiles:
        prof = loadgen.profile(name)
        for scale in rate_scales:
            rep = loadgen.run_load(client, prof, seed=seed,
                                   n=n_requests, rate_scale=scale,
                                   mode="open", recorder=recorder)
            reports.append(rep)
            row = loadgen.pareto_row(rep)
            row["workload"] = f"{name}_x{scale:g}".replace(".", "p")
            pareto.append(row)
            if scale == rate_scales[0]:
                srow = loadgen.slo_row(rep)
                srow["workload"] = name
                slo_rows.append(srow)
    return {
        "seed": seed,
        "requests_per_run": n_requests,
        "pareto": pareto,
        "slo_rows": slo_rows,
        # the measured TRAFFIC-MIX signal (ISSUE 12 satellite, the
        # ROADMAP item-5/6 follow-on AUTO_PLAN continuous mode needs):
        # demand + goodput-under-SLO + induced occupancy per
        # (profile, rate) — loadgen.traffic_mix_row over the same runs
        "traffic_mix": loadgen.traffic_mix_row(reports)["workloads"],
        "occupancy": loadgen.occupancy_summary(since_ms=occ_since),
    }


def measure_fleet_scaling(seed: int = 0, n_requests: int = 16) -> dict:
    """graftfleet scaling row (ISSUE 12): the disaggregated fleet —
    router + 1 prefill replica + N decode replicas over ONE shared
    pool — driven by the bursty_chat profile at 1 vs 2 decode
    replicas. The deep-shared-prefix workload is the fleet's favorable
    case (the prefill replica warms the content-keyed registry once,
    affinity routing keeps adoptions local), so this row is the
    replica-scaling signal: throughput/goodput per decode-replica
    count plus the router's affinity hit rate and typed-shed split.

    Needs the bench chip: on CPU the decode itself dominates and a
    second replica would measure host contention, not serving scale.
    """
    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "fleet replica scaling needs the bench chip "
                           "(on CPU the decode itself dominates and a "
                           "second replica measures host contention, "
                           "not serving scale)"}

    from llm_sharding_demo_tpu import loadgen
    from llm_sharding_demo_tpu.fleet import build_fleet

    prof = loadgen.profile("bursty_chat")
    rows = []
    for n_decode in (1, 2):
        f = build_fleet(n_decode=n_decode, n_prefill=1,
                        max_seq=256, kv_pool_blocks=0,
                        recorder_capacity=max(64, 2 * n_requests))
        # warmup/compile pass so the open-loop tails measure serving
        loadgen.run_load(f.client, prof, seed=seed + 1, n=2,
                         mode="serial", recorder=f.recorder)
        # affinity_stats is cumulative — snapshot after warmup so the
        # journaled (gated) rates cover only the measured run
        base = f.app.router.affinity_stats()
        rep = loadgen.run_load(f.client, prof, seed=seed,
                               n=n_requests, rate_scale=2.0,
                               mode="open", recorder=f.recorder)
        stats = {k: v - base[k]
                 for k, v in f.app.router.affinity_stats().items()}
        routed = stats["hits"] + stats["fallbacks"]
        rows.append({
            "workload": f"decode_x{n_decode}",
            "decode_replicas": n_decode,
            "offered_rps": rep["offered_rps"],
            "completed": rep["completed"],
            "throughput_tokens_per_sec":
                rep["throughput_tokens_per_sec"],
            "goodput_rps": rep["goodput_rps"],
            "goodput_fraction": rep["goodput_fraction"],
            "p99_e2e_ms": rep["p99_e2e_ms"],
            "shed_429": rep["shed_429"],
            "shed_503": rep["shed_503"],
            "affinity_hit_rate": round(stats["hits"] / routed, 4)
            if routed else 0.0,
            "replica_sheds": stats["sheds"],
        })
    return {"seed": seed, "requests_per_run": n_requests,
            "workloads": rows}


def measure_plan_switch(seed: int = 7, n_requests: int = 10) -> dict:
    """graftwatch live re-planning row (ISSUE 13): the seeded mix flip
    (serial single-stream -> open burst -> serial again, agentic
    profile) against the AUTO_PLAN_CONTINUOUS app — the bench-grade
    twin of tests/test_graftwatch.py's acceptance run. Journals the
    live switch count, goodput/throughput before (solo plan, serial
    phase) and after (batched plan, burst phase) the switch, and the
    pinned invariant as a number: compiled programs minted by replaying
    the whole mix across further live switches — ZERO beyond the
    pre-certified set, gated lower-better by bench_diff so any upward
    drift reads as a certified-envelope leak, not noise.

    Needs the bench chip: on CPU the decode itself dominates and the
    open-loop burst would measure the host, not the switch.
    """
    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "plan-switch goodput needs the bench chip "
                           "(on CPU the decode itself dominates and "
                           "the open-loop burst measures the host, "
                           "not the live re-planner)"}

    from llm_sharding_demo_tpu import loadgen
    from tools.graftload import build_demo_app

    prof = loadgen.profile("agentic")
    sched = loadgen.schedule(prof, seed, n_requests)
    # certify the plan set against the schedule's OWN traffic classes
    # (byte-level prompt lengths — the demo app's ByteTokenizer), so
    # the certified bounds cover the whole measured run
    classes = sorted({(len(a.prompt.encode("utf-8")), a.max_new)
                      for a in sched})
    traffic = ",".join(f"{p}/{n}" for p, n in classes)
    client, recorder, _reg = build_demo_app(
        max_seq=256, max_batch=4, recorder_capacity=max(64, 8 * n_requests),
        continuous=True, auto_plan_traffic=traffic)
    sw = client.app.plan_switcher

    def caches():
        solo = sw.plans["solo"]
        eng, pool = solo.engine, solo.pool
        return sum(fn._cache_size() for fn in (
            eng._prefill, eng._prefill_chunked, eng._decode_seg,
            pool._gather, pool._scatter, pool._scatter_row, pool._copy))

    def run(mode, rate=1.0):
        return loadgen.run_load(client, prof, seed=seed, n=n_requests,
                                mode=mode, rate_scale=rate,
                                recorder=recorder)

    # warmup/compile pass so phase goodput measures serving, not
    # first-touch XLA compiles
    loadgen.run_load(client, prof, seed=seed + 1, n=2, mode="serial",
                     recorder=recorder)
    before = run("serial")            # single-stream: stays solo
    burst = run("open", rate=60.0)    # the burst: flips to batched
    run("serial")                     # drains back toward solo
    programs_after_mix = caches()
    # the full mix again: more live switches, zero new programs is the
    # journaled invariant
    run("serial")
    after = run("open", rate=60.0)
    run("serial")
    recompiles = caches() - programs_after_mix
    hv = sw.health_view()
    return {
        "seed": seed,
        "requests_per_run": n_requests,
        "switches": hv["switches"],
        "switch_flips": [f'{e["from"]}->{e["to"]}'
                         for e in sw.events() if e["switched"]],
        "active_plan": hv["active"],
        "certified_program_total": sum(
            sw.certified[p]["program_total"] for p in sw.certified),
        # THE invariant, as a gated metric (lower-better, expect 0)
        "recompiles_beyond_certified": recompiles,
        "goodput_fraction_before": before["goodput_fraction"],
        "goodput_fraction_after": after["goodput_fraction"],
        "throughput_tokens_per_sec_before":
            before["throughput_tokens_per_sec"],
        "throughput_tokens_per_sec_after":
            after["throughput_tokens_per_sec"],
        "p99_e2e_ms_before": before["p99_e2e_ms"],
        "p99_e2e_ms_burst": burst["p99_e2e_ms"],
        "p99_e2e_ms_after": after["p99_e2e_ms"],
    }


def measure_spec_iterbatch(config, dtype="bfloat16", n_requests: int = 8,
                           max_batch: int = 4, steps: int = 160,
                           prompt_len: int = 64, stagger_s: float = 0.04,
                           seg_steps: int = 64, draft_len: int = 6) -> dict:
    """Speculation x continuous batching — the composition this repo's
    two strongest serving optimizations could not reach before: the SAME
    staggered multi-request workload through (a) the plain iteration
    scheduler (one token per forward per row) and (b) the iteration
    scheduler running draft-verify segments (runtime.spec_decode._seg_b,
    per-row acceptance + uniform-depth re-sync).

    The workload is REPETITIVE (periodic prompt), the favorable case for
    prompt-lookup drafting — exactly the serving profile (templated
    outputs, code, few-shot continuations) the composition targets; the
    acceptance column contextualizes the speedup the way cfg8 does for
    the solo case. Exactness is pinned by tests (every spec row
    byte-equal to its solo speculative run); this row measures the
    aggregate tokens/sec the composition buys."""
    import threading as _th

    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import SamplingConfig
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
    from llm_sharding_demo_tpu.runtime.spec_decode import SpecDecodeEngine

    params = gpt2.init_params(config, jax.random.PRNGKey(0),
                              dtype=jnp.float32)
    bucketed = (prompt_len + 15) // 16 * 16
    max_seq = min(config.n_positions,
                  bucketed + 4 * steps + draft_len)
    spec = SpecDecodeEngine(params, config, max_seq=max_seq, dtype=dtype,
                            draft_len=draft_len)
    engine = spec.plain
    # periodic prompt: greedy continuation loops, so lookup drafts land
    period = np.asarray([11, 29, 3, 47, 5, 17, 23, 2], dtype=np.int32)
    prompt = np.tile(period, prompt_len // len(period) + 1)[:prompt_len]

    def drive(ib, sampling) -> float:
        done = [None] * n_requests

        def run(i):
            time.sleep(i * stagger_s)
            done[i] = ib.generate(prompt, steps, sampling=sampling)

        t0 = time.perf_counter()
        threads = [_th.Thread(target=run, args=(i,))
                   for i in range(n_requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        assert all(r is not None for r in done)
        return n_requests * steps / dt

    results = {}
    for name, sampling in (("plain", SamplingConfig()),
                           ("spec", SamplingConfig(spec=True))):
        ib = IterBatchingEngine(engine, max_batch=max_batch,
                                seg_steps=seg_steps, max_wait_ms=5.0,
                                spec=spec)
        drive(ib, sampling)          # warmup: compiles + caches programs
        before = (spec.stats(), ib.stats())
        results[name] = drive(ib, sampling)
        if name == "spec":
            s_after, ib_after = spec.stats(), ib.stats()
            verifies = s_after["verify_steps"] - before[0]["verify_steps"]
            emitted = (s_after["emitted_tokens"]
                       - before[0]["emitted_tokens"])
            results["accept"] = round(emitted / max(verifies, 1), 2)
            results["spec_segments"] = (ib_after["spec_segments"]
                                        - before[1]["spec_segments"])
            results["joins"] = ib_after["joins"] - before[1]["joins"]
    return {
        "iter_tokens_per_sec": round(results["plain"], 1),
        "spec_iter_tokens_per_sec": round(results["spec"], 1),
        "spec_vs_plain_iter": round(results["spec"] / results["plain"], 2),
        "accepted_tokens_per_verify": results["accept"],
        "draft_len": draft_len, "n_requests": n_requests,
        "max_batch": max_batch, "steps": steps,
        "seg_steps": seg_steps, "spec_segments": results["spec_segments"],
        "joins": results["joins"],
        "stagger_ms": round(stagger_s * 1e3, 1),
    }


def measure_training(config, batch: int = 8, seq: int = 512,
                     dtype_name: str = "bfloat16") -> dict:
    """Single-chip jitted train step (fwd + bwd + AdamW, remat): tokens/s
    and achieved MFU. The training subsystem had correctness tests but no
    measured perf before round 3 (VERDICT r2 missing #3).

    MFU convention: model FLOPs = 6 * n_params per token (fwd 2N + bwd
    4N; attention FLOPs and the remat recompute are excluded, the
    standard accounting), against the attached device kind's bf16 peak
    (emitted as ``peak_flops``; MFU is omitted when the peak is unknown,
    e.g. on the CPU fallback).
    """
    import jax
    import jax.numpy as jnp

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.training import train

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    params = gpt2.init_params(config, jax.random.PRNGKey(0), dtype=dtype)
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(params))
    step = train.TrainStep(config, train.adamw(1e-3), remat=True)
    p, opt = step.init(params)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, seq + 1)), jnp.int32)

    def make(n):
        @jax.jit
        def run(p, opt, ids):
            def body(i, carry):
                p, opt, _ = carry
                return step._step(p, opt, ids)
            return jax.lax.fori_loop(0, n, body,
                                     (p, opt, jnp.zeros((), jnp.float32)))
        return run

    compiled = {}

    def time_window(n):
        if n not in compiled:
            compiled[n] = make(n)
        t0 = time.perf_counter()
        _, _, loss = compiled[n](p, opt, ids)
        _fetch(loss)
        return time.perf_counter() - t0

    m = marginal_seconds(time_window, 2, 8, reps=3)
    if m is None:
        return {"error": "marginal below timer resolution"}
    tokens_per_sec = batch * seq / m
    out = {
        "tokens_per_sec": round(tokens_per_sec, 1),
        "step_ms": round(m * 1e3, 2),
        "batch": batch, "seq": seq, "n_params": n_params,
    }
    peak = _peak_bf16_flops()
    if peak is not None:  # MFU only when the device's peak is known —
        out["peak_flops"] = peak  # a hard-coded v5e peak would silently
        out["mfu"] = round(tokens_per_sec * 6 * n_params / peak, 4)
        # mislabel MFU on other backends (incl. the CPU fallback)
    return out


def _peak_bf16_flops():
    """Dense bf16 peak for the attached device kind, or None when unknown
    (CPU fallback, unrecognized TPU generation)."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    for tag, peak in (("v5 lite", 197e12), ("v5e", 197e12),
                      ("v5p", 459e12), ("v5", 459e12),
                      ("v6 lite", 918e12), ("v6e", 918e12),
                      ("v4", 275e12)):
        if tag in kind:
            return peak
    return None


def measure_gpipe_overhead() -> dict:
    """Pipeline schedules (GPipe and 1F1B, pp4 x dp2) vs pure dp8, same
    model and global batch, on an 8-device virtual CPU mesh (the only
    multi-device environment the bench has): the ratios are the
    schedules' overheads — the numbers behind parallel.gpipe's
    bubble-skip claim and parallel.pipeline_1f1b's schedule upgrade.
    Absolute CPU times are meaningless; only the ratios are reported.
    1F1B runs M=8 microbatches (its bounded stash is what makes large M
    affordable — the schedule's whole point); GPipe keeps its M=4 row
    for continuity with earlier rounds."""
    import json as _json
    import subprocess
    import sys

    code = r"""
import os, time, json
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from llm_sharding_demo_tpu.models import gpt2
from llm_sharding_demo_tpu.parallel import spmd
from llm_sharding_demo_tpu.training import train

cfg = gpt2.GPT2Config(vocab_size=2048, n_positions=256, n_embd=256,
                      n_layer=8, n_head=8)
params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
ids = jnp.asarray(np.random.default_rng(0).integers(
    0, cfg.vocab_size, size=(8, 129)), jnp.int32)

def time_steps(step, p, opt, batch, n=3):
    p, opt, loss = step(p, opt, batch); jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(n):
        p, opt, loss = step(p, opt, batch)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / n

dp_mesh = spmd.make_mesh({"dp": 8}, jax.devices())
dp = train.TrainStep(cfg, train.adamw(1e-3), mesh=dp_mesh)
pdp, odp = dp.init(params)
t_dp = time_steps(dp, pdp, odp, dp.shard_batch(ids))

gp_mesh = spmd.make_mesh({"dp": 2, "pp": 4}, jax.devices())
gp = train.GPipeTrainStep(cfg, train.adamw(1e-3), gp_mesh, n_microbatches=4)
pgp, ogp = gp.init(params)
t_gp = time_steps(gp, pgp, ogp, gp.shard_batch(ids))

fb = train.GPipeTrainStep(cfg, train.adamw(1e-3), gp_mesh, n_microbatches=8,
                          schedule="1f1b")
pfb, ofb = fb.init(params)
t_fb = time_steps(fb, pfb, ofb, fb.shard_batch(ids))

iv = train.GPipeTrainStep(cfg, train.adamw(1e-3), gp_mesh, n_microbatches=8,
                          schedule="1f1b", virtual_stages=2)
piv, oiv = iv.init(params)
t_iv = time_steps(iv, piv, oiv, iv.shard_batch(ids))
print(json.dumps({"dp8_step_s": round(t_dp, 4),
                  "pp4dp2_step_s": round(t_gp, 4),
                  "gpipe_vs_dp": round(t_gp / t_dp, 2),
                  "pp4dp2_1f1b_step_s": round(t_fb, 4),
                  "1f1b_vs_dp": round(t_fb / t_dp, 2),
                  "1f1b_vs_gpipe": round(t_fb / t_gp, 2),
                  "pp4dp2_1f1b_v2_step_s": round(t_iv, 4),
                  "1f1b_interleaved_v2_vs_dp": round(t_iv / t_dp, 2)}))
"""
    import os
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=1200,
                         cwd=os.path.dirname(os.path.abspath(__file__)))
    if out.returncode != 0:
        return {"error": out.stderr.strip()[-300:]}
    return _json.loads(out.stdout.strip().splitlines()[-1])


# ---------------------------------------------------------------------------
# Capture-proofing (VERDICT r4 missing #1): BENCH_r04.json was voided by a
# backend-init error that escaped the per-config isolation (rc=1, parsed:
# null) — and the same tunnel outage can also HANG instead of erroring
# (jax.devices() blocks forever).  The driver contract is one parseable
# JSON line no matter what, so the measurement now runs in a CHILD process
# under a parent that (a) probes the backend with bounded retries before
# committing to a run, (b) enforces a hard wall-clock watchdog, and
# (c) on any child failure still emits a line assembled from the rows the
# child completed (each safe() row is journaled to a progress file).
# ---------------------------------------------------------------------------
_CHILD_SENTINEL = "_BENCH_CHILD"
_PROGRESS_ENV = "_BENCH_PROGRESS_FILE"
_PROBE_ATTEMPTS = 3
_PROBE_TIMEOUT_S = 150
_PROBE_BACKOFF_S = 30
_HEADLINE_METRIC = "greedy_decode_throughput_gpt2_124m"
_QUICK_METRIC = "greedy_decode_throughput_tiny"


def _run_child(cmd, *, env, cwd, timeout_s) -> int:
    """Run the measurement child, streaming its output through the
    shared AOT-spew filter + watchdog (utils.subproc) — the driver's
    output-tail capture must keep the final JSON line in view."""
    from llm_sharding_demo_tpu.utils.subproc import run_filtered
    return run_filtered(cmd, env=env, cwd=cwd, timeout_s=timeout_s)


def _journal_row(row: dict) -> None:
    """Append one finished config row to the parent's progress file (the
    partial-artifact fallback when the child dies mid-matrix)."""
    progress = os.environ.get(_PROGRESS_ENV)
    if not progress:
        return
    try:
        with open(progress, "a") as f:
            f.write(json.dumps(row) + "\n")
    except OSError:
        pass


def _probe_backend(attempts: int = _PROBE_ATTEMPTS) -> tuple:
    """(platform, None) if a default backend answers within bounded time,
    else (None, reason). Subprocess + timeout via the shared helper:
    with the tunnel down, in-process jax.devices() can block forever.
    ``attempts=1`` is the smoke-run mode (fail fast, no retry tax)."""
    from llm_sharding_demo_tpu.utils.backend_probe import (
        probe_default_backend)
    return probe_default_backend(_PROBE_TIMEOUT_S, attempts=attempts,
                                 backoff_s=_PROBE_BACKOFF_S)


def _parent_main(argv) -> None:
    """Probe, then run the real bench in a watchdogged child; ALWAYS end
    with one parseable JSON line on stdout."""
    import sys
    import tempfile

    quick = "--quick" in argv
    metric = _QUICK_METRIC if quick else _HEADLINE_METRIC
    # a smoke run fails fast (one probe attempt, no ~9-minute retry tax)
    platform, reason = _probe_backend(attempts=1 if quick else
                                      _PROBE_ATTEMPTS)
    if platform is None:
        # A dead tunnel must not silently OMIT configs the round is
        # watching: record the headline composition row as skipped-with-
        # reason so downstream artifact diffs see "not measured", never
        # "dropped" (the full matrix would be noise; the spec x iter
        # row is the one a trajectory reader would miss).
        skipped = [{"name": "cfg13_spec_iterbatch_staggered",
                    "skipped": f"backend unavailable: {reason}"}]
        emit({"metric": metric, "value": None,
              "unit": "tokens/sec", "vs_baseline": None,
              "skipped": f"backend unavailable: {reason}",
              "configs": [] if quick else skipped},
             write_file=False)
        return

    fd, progress = tempfile.mkstemp(prefix="bench_progress_", suffix=".jsonl")
    os.close(fd)
    env = dict(os.environ)
    env[_CHILD_SENTINEL] = "1"
    env[_PROGRESS_ENV] = progress
    here = os.path.abspath(__file__)
    budget = 1500 if quick else 5400
    try:
        rc = _run_child([sys.executable, here] + list(argv), env=env,
                        cwd=os.path.dirname(here), timeout_s=budget)
        if rc == 0:
            return  # child printed the line (and wrote the matrix file)
        reason = f"bench child exited rc={rc}"
    except TimeoutError:
        reason = f"bench child exceeded {budget}s watchdog"
    finally:
        rows = []
        try:
            with open(progress) as f:
                rows = [json.loads(ln) for ln in f if ln.strip()]
        except OSError:
            pass
        try:
            os.unlink(progress)
        except OSError:
            pass
    by_name = {c.get("name"): c for c in rows}
    head = (by_name.get("cfg1_tiny_gpt2_2shard_20tok", {}) if quick
            else by_name.get("cfg2_gpt2_124m_2shard_single_prompt", {}))
    value = (head.get("tokens_per_sec") if quick
             else head.get("engine_bf16_tokens_per_sec"))
    vs = (head.get("vs_baseline") if quick
          else head.get("engine_bf16_vs_baseline"))
    emit({"metric": metric, "value": value, "unit": "tokens/sec",
          "vs_baseline": vs, "error": reason, "partial": True,
          "configs": rows}, write_file=False)


def main() -> None:
    import os
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="cfg1 only (tiny model) for a fast smoke run")
    args = parser.parse_args()

    if not os.environ.get(_CHILD_SENTINEL):
        _parent_main(sys.argv[1:])
        return

    from llm_sharding_demo_tpu.models import gpt2

    tiny, g124, gmed = (gpt2.CONFIGS[k]
                        for k in ("tiny-gpt2", "gpt2", "gpt2-medium"))
    configs = []
    try:
        rtt_ms = measure_dispatch_rtt()
    except Exception as e:  # noqa: BLE001 — a dead rtt probe must not
        rtt_ms = None       # void the artifact; rtt-dependent rows error
        row = {"name": "dispatch_rtt",          # individually via safe()
               "error": f"{type(e).__name__}: {e}"}
        configs.append(row)
        # journaled like every safe() row: if the child later dies, the
        # parent's partial-artifact fallback keeps the rtt-probe error
        # context instead of silently dropping it
        _journal_row(row)

    # cfg1: tiny-gpt2, 2-shard, 20 tokens — the notebook workload, timed
    # e2e as mandated. With ~2 dispatches x rtt_ms of tunnel latency in a
    # sub-second workload, this row is RTT-bound by construction; the
    # steady-state row shows what the chip itself does.
    def cfg1():
        ref_tiny = measure_reference_cpu(tiny, 4, 20)
        pipe_tiny = measure_pipeline(tiny, 2, 4, two_point=False,
                                     new_tokens=20)
        fused = measure_single_program_e2e(tiny, 4, 20)
        if rtt_ms is None:  # rtt probe died: keep the real measurements,
            return {        # just drop the rtt-derived context fields
                "tokens_per_sec": round(pipe_tiny["tokens_per_sec"], 2),
                "single_program_tokens_per_sec": round(
                    fused["tokens_per_sec"], 1),
                "ref_cpu_tokens_per_sec": round(ref_tiny, 2),
                "vs_baseline": round(
                    pipe_tiny["tokens_per_sec"] / ref_tiny, 2),
                "single_program_vs_baseline": round(
                    fused["tokens_per_sec"] / ref_tiny, 2),
                "transfer_rtt_ms": None,
                "note": "rtt probe failed; see dispatch_rtt error row",
            }
        rtt_bound = 20 / (rtt_ms / 1e3)
        return {
            "tokens_per_sec": round(pipe_tiny["tokens_per_sec"], 2),
            "single_program_tokens_per_sec": round(
                fused["tokens_per_sec"], 1),
            "ref_cpu_tokens_per_sec": round(ref_tiny, 2),
            "vs_baseline": round(pipe_tiny["tokens_per_sec"] / ref_tiny, 2),
            "single_program_vs_baseline": round(
                fused["tokens_per_sec"] / ref_tiny, 2),
            "transfer_rtt_ms": round(rtt_ms, 1),
            "rtt_bound_tokens_per_sec": round(rtt_bound, 1),
            "note": "2-stage single-program pipeline, "
                    + pipe_tiny["placement"]
                    + "; single_program_* = the whole 20-token workload as "
                      "ONE compiled program closed by ONE fetch (prefill + "
                      "scanned decode) — it lands AT the tunnel's RTT "
                      f"bound of 20 tok / {rtt_ms:.0f} ms = "
                      f"{rtt_bound:.0f} tok/s, which is below the "
                      "reference CPU's in-process rate for this 2-dim toy "
                      "(~µs/token of compute, zero RTT): vs_baseline > 1 "
                      "is arithmetically impossible over this tunnel for "
                      "a sub-second workload. See cfg2 for steady-state "
                      "chip rates",
        }

    # Each config runs isolated: one failing measurement must not cost the
    # round its whole BENCH artifact — the failed row records the error
    # and the rest of the matrix still reports.
    def safe(name: str, fn) -> None:
        import traceback

        from llm_sharding_demo_tpu.utils.metrics import REGISTRY
        before = REGISTRY.snapshot()
        try:
            row = {"name": name, **fn()}
        except Exception as e:  # noqa: BLE001 — report, don't die
            row = {"name": name, "error": f"{type(e).__name__}: {e}",
                   "traceback_tail":
                       traceback.format_exc().strip()[-600:]}
        delta = _metrics_delta(before, REGISTRY.snapshot())
        if delta:
            row["metrics_delta"] = delta
        configs.append(row)
        _journal_row(row)

    def cfg_graftcheck():
        """Static-analysis journal row (ISSUE 3): the graftcheck --json
        payload rides the perf matrix, so contract drift (new lint
        findings, changed recompile bounds, stale baseline entries)
        lands in the same trajectory as the timings. Cheap (a few
        seconds of AST walking + abstract eval, no tunnel dependency)
        and journaled FIRST — before any chip-bound row — so a
        timeout-cut run still records it."""
        import sys as _sys
        here = os.path.dirname(os.path.abspath(__file__))
        added = here not in _sys.path
        if added:
            _sys.path.insert(0, here)
        try:
            from tools.graftcheck import cli as _gc
            payload = _gc.run(root=here)
        finally:
            if added:
                try:
                    _sys.path.remove(here)
                except ValueError:
                    pass
        return {
            "ok": payload["ok"],
            "active_findings": len(payload["findings"]),
            # full finding rows only when something is wrong — the OK
            # case stays one compact journal line
            **({"findings": payload["findings"]}
               if payload["findings"] else {}),
            "suppressed": payload["suppressed"],
            "stale_baseline": payload["stale_baseline"],
            "semantic_checks": payload["semantic_checks"],
            "sanitize_checks": payload["sanitize_checks"],
            "locks_checks": payload["locks_checks"],
            "locks_vacuous": payload["locks_vacuous"],
            "slo_checks": payload["slo_checks"],
            "slo_vacuous": payload["slo_vacuous"],
            "numerics_checks": payload["numerics_checks"],
            "numerics_vacuous": payload["numerics_vacuous"],
            "memory_checks": payload["memory_checks"],
            "memory_ledgers": payload["memory_ledgers"],
            "memory_vacuous": payload["memory_vacuous"],
            "recompile_bounds": payload["recompile_bounds"],
        }

    def cfg_graftplan():
        """Chosen-plan journal row (ISSUE 6): the auto-sharding
        planner's pick for the bench model on this host's devices rides
        the perf matrix next to graftcheck_static_analysis, so a cost-
        model change that flips the chosen serving config shows up in
        the same trajectory as the timings it would cause. Compile-free
        (abstract eval only), no tunnel dependency."""
        import sys as _sys
        here = os.path.dirname(os.path.abspath(__file__))
        added = here not in _sys.path
        if added:
            _sys.path.insert(0, here)
        try:
            import jax as _jax

            from tools.graftcheck import costmodel as _cm, registry as _reg
            module, config = _reg.planner_families()["gpt2-tiny"]
            payload = _cm.plan_for_serving(
                config, len(_jax.devices()), max_seq=64,
                traffic=_cm.parse_traffic("16/32x8"), max_batch_cap=8,
                kv_pool_blocks=32)
        finally:
            if added:
                try:
                    _sys.path.remove(here)
                except ValueError:
                    pass
        chosen = payload["chosen"]
        return {
            "devices": len(_jax.devices()),
            "traffic": "16/32x8",
            "chosen": chosen,
            "candidates": len(payload["plan"]),
            "rejected": payload["rejected"],
        }

    def cfg_ici_calibration():
        """ICI_BYTE_WEIGHT calibration row (ROADMAP item 5 follow-on):
        measured-vs-modeled comm bytes for the pp=2 ppdecode ring. The
        cost model walks collective bytes off the traced decode step
        (tools/graftcheck/costmodel.py, ICI_BYTE_WEIGHT = relative cost
        of an ICI byte vs an HBM byte); this row compiles THE SAME step
        on the real 2-device pp mesh and journals what the executable's
        own cost analysis reports for the transfer, so a drift between
        the model's byte formula and what XLA actually schedules lands
        in the perf trajectory. Needs the bench chip with >= 2 devices:
        CPU 'collectives' are host memcpys and would calibrate nothing.
        """
        import jax as _jax

        from tools.graftcheck import costmodel as _cm

        if _jax.default_backend() != "tpu":
            return {"skipped": "ICI calibration needs the bench chip "
                               "(CPU collectives are host memcpys; a "
                               "measured/modeled ratio there would "
                               "mislead the planner's ICI_BYTE_WEIGHT)"}
        if len(_jax.devices()) < 2:
            return {"skipped": "ICI calibration needs >= 2 devices for "
                               "a real pp=2 ring; this host exposes "
                               f"{len(_jax.devices())}"}

        from llm_sharding_demo_tpu.models import gpt2 as _g
        from llm_sharding_demo_tpu.parallel.spmd import make_mesh
        modeled = _cm.pp_decode_comm_bytes(2, batch=1, module=_g,
                                           config=tiny)
        mesh = make_mesh({"pp": 2}, _jax.devices()[:2])
        fn, args = _cm.pp_decode_step_program(2, batch=1, module=_g,
                                              config=tiny, mesh=mesh)
        compiled = _jax.jit(fn).lower(*args).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        measured = None
        measured_key = None
        for key, val in sorted((analysis or {}).items()):
            if "network" in key.lower():
                measured = (measured or 0.0) + float(val)
                measured_key = key if measured_key is None \
                    else f"{measured_key}+{key}"
        hlo_permutes = compiled.as_text().count("collective-permute")
        row = {
            "modeled_comm_bytes_per_token": modeled,
            "measured_comm_bytes_per_token": measured,
            "measured_source": measured_key or "cost_analysis had no "
                                               "network counters",
            "hlo_collective_permutes": hlo_permutes,
            "ici_byte_weight": _cm.ICI_BYTE_WEIGHT,
            "note": "pp=2 ppdecode ring decode step; ratio calibrates "
                    "the planner's ICI byte weight against the "
                    "compiled executable",
        }
        if measured and modeled:
            row["measured_over_modeled"] = round(measured / modeled, 3)
        return row

    def cfg_graftscope_attribution():
        """Measured-vs-modeled attribution row (ISSUE 9): replay the
        canonical workloads on tiny real engines with device-true
        dispatch timing (graftscope sync mode) and join the observed
        program rings against the recompile certifier's key sets —
        exact rows must join 1:1 — plus the implied byte rate against
        the cost model's per-token prediction. Compile-cheap, CPU-safe,
        no tunnel dependency; the drift trajectory rides the journal."""
        import sys as _sys
        here = os.path.dirname(os.path.abspath(__file__))
        added = here not in _sys.path
        if added:
            _sys.path.insert(0, here)
        try:
            from tools.graftcheck import scope as _scope
            payload = _scope.run_attribution()
        finally:
            if added:
                try:
                    _sys.path.remove(here)
                except ValueError:
                    pass
        return {
            "ok": payload["ok"],
            "workloads": [
                {k: v for k, v in row.items() if k != "entry_points"}
                for row in payload["workloads"]],
            "note": payload["note"],
        }

    def cfg_numerics_oracle():
        """graftnum tolerance-oracle row (ISSUE 15): every declared
        TOLERANCE_POLICY path (int8 weight-only, bf16 decode, quantized
        KV blocks) measured against the f32 parity engine on the PINNED
        seed — per-path logit MSE (lower-better) and greedy top-1
        agreement (higher-better), gated by tools/bench_diff.py so a
        quantizer or mixed-precision regression lands in the trajectory
        as a numerics drift, not a mystery token flip. Seeded and
        replay-identical (tests/test_graftnum.py pins byte-identical
        reports across fresh runs); CPU-safe, no tunnel dependency —
        the oracle RAISES on a declared-budget breach, so this row
        erroring is itself the signal."""
        from llm_sharding_demo_tpu.utils import graftnum

        rows = graftnum.oracle_rows(seed=0)
        flat = {"seed": 0, "paths": len(rows)}
        for r in rows:
            # flatten per-path metrics so bench_diff gates them:
            # decode_int8_logit_mse / decode_int8_top1_agreement / ...
            # — the FULL path keys the row, so two policy paths sharing
            # a suffix (decode.int8 vs kv.int8) can never silently
            # shadow each other's gated metrics
            tag = r["path"].replace(".", "_")
            if "skipped" in r:
                # backend-prerequisite skip (fp8 storage on an old
                # chip): journal WHY, so the gated set shrinking is a
                # recorded fact, never a silent hole in the trajectory
                flat[f"{tag}_skipped"] = r["skipped"]
                continue
            flat[f"{tag}_logit_mse"] = r["logit_mse"]
            flat[f"{tag}_top1_agreement"] = r["top1_agreement"]
            flat[f"{tag}_positions"] = r["n_positions"]
        return flat

    safe("graftcheck_static_analysis", cfg_graftcheck)
    safe("graftcheck_chosen_plan", cfg_graftplan)
    safe("numerics_oracle", cfg_numerics_oracle)
    safe("graftscope_attribution", cfg_graftscope_attribution)
    safe("ici_byte_weight_calibration", cfg_ici_calibration)
    safe("cfg1_tiny_gpt2_2shard_20tok", cfg1)

    if args.quick:
        row = next((c for c in configs
                    if c["name"] == "cfg1_tiny_gpt2_2shard_20tok"), {})
        emit({
            "metric": _QUICK_METRIC,
            "value": row.get("tokens_per_sec"),
            "unit": "tokens/sec",
            "vs_baseline": row.get("vs_baseline"),
            "configs": configs,
        }, write_file=False)
        return

    # Shared 124M baseline: the reference O(n^2) loop, 20 tokens. Guarded
    # like the config rows: if the CPU denominator itself fails, TPU rows
    # still report absolute rates with vs_baseline = null.
    try:
        ref_124 = measure_reference_cpu(g124, PROMPT_LEN, 20)
    except Exception as e:  # noqa: BLE001
        configs.append({"name": "ref_cpu_gpt2_124m",
                        "error": f"{type(e).__name__}: {e}"})
        ref_124 = None

    def vs_ref(x):
        return None if ref_124 is None else round(x / ref_124, 2)

    def ref_cpu():
        return None if ref_124 is None else round(ref_124, 2)

    def cfg2():
        # 124M single stream — 2-shard pipeline AND the fused single-chip
        # engine (fp32 parity mode + bf16 fast path).
        pipe_124 = measure_pipeline(g124, 2, PROMPT_LEN, 1, "bfloat16")
        eng_f32 = measure_engine(g124, PROMPT_LEN, 1, "float32")
        eng_bf16 = measure_engine(g124, PROMPT_LEN, 1, "bfloat16")
        eng_int8 = measure_engine(g124, PROMPT_LEN, 1, "int8")
        return {
            "tokens_per_sec": round(pipe_124["tokens_per_sec"], 2),
            "engine_fp32_tokens_per_sec": round(eng_f32["tokens_per_sec"], 2),
            "engine_bf16_tokens_per_sec": round(eng_bf16["tokens_per_sec"], 2),
            "engine_int8_tokens_per_sec": round(eng_int8["tokens_per_sec"], 2),
            "p50_token_latency_ms": round(eng_bf16["p50_token_latency_ms"], 3),
            "e2e_tokens_per_sec": round(eng_bf16["e2e_tokens_per_sec"], 2),
            "ref_cpu_tokens_per_sec": ref_cpu(),
            "vs_baseline": vs_ref(pipe_124["tokens_per_sec"]),
            "engine_bf16_vs_baseline": vs_ref(eng_bf16["tokens_per_sec"]),
            "engine_int8_vs_baseline": vs_ref(eng_int8["tokens_per_sec"]),
            "note": "steady-state (marginal) decode rates; 2-stage bf16 "
                    "pipeline, " + pipe_124["placement"]
                    + "; engine rows are the unstaged single-chip path "
                      "(fp32 = parity mode, bf16 = fast, int8 = weight-only "
                      "quantized fast path)",
        }

    def cfg3():
        # 124M batch=8. Reference baseline: 8 sequential bs=1 streams ==
        # the same tokens/sec (server.py:137 hardcodes batch 1).
        b8_f32 = measure_engine(g124, PROMPT_LEN, 8, "float32")
        b8_bf16 = measure_engine(g124, PROMPT_LEN, 8, "bfloat16")
        return {
            "tokens_per_sec": round(b8_bf16["tokens_per_sec"], 2),
            "engine_fp32_tokens_per_sec": round(b8_f32["tokens_per_sec"], 2),
            "ref_cpu_tokens_per_sec": ref_cpu(),
            "vs_baseline": vs_ref(b8_bf16["tokens_per_sec"]),
            "note": "aggregate steady-state tokens/sec over 8 rows; "
                    "reference can only run them sequentially at its bs=1 "
                    "rate",
        }

    def cfg4():
        ref_med = measure_reference_cpu(gmed, PROMPT_LEN, 10)
        pipe_med = measure_pipeline(gmed, 4, PROMPT_LEN, 1, "bfloat16")
        return {
            "tokens_per_sec": round(pipe_med["tokens_per_sec"], 2),
            "ref_cpu_tokens_per_sec": round(ref_med, 2),
            "vs_baseline": round(pipe_med["tokens_per_sec"] / ref_med, 2),
            "placement": pipe_med["placement"],
            "note": "steady-state bf16 4-stage pipeline; baseline is the "
                    "reference algorithm on gpt2-medium",
        }

    def cfg5():
        # KV cache vs O(n^2) — both on this framework, same chip. Long
        # window (most of the position table): at short sequences a fast
        # chip hides the O(n^2) compute behind weight streaming.
        long_steps = g124.n_positions - PROMPT_LEN - 16
        uncached = measure_uncached_jax(g124, PROMPT_LEN, long_steps)
        cached_long = measure_engine(g124, PROMPT_LEN, 1, "bfloat16",
                                     s_b=long_steps)
        return {
            "tokens_per_sec": round(cached_long["tokens_per_sec"], 2),
            "uncached_jax_tokens_per_sec":
                None if uncached is None else round(uncached, 2),
            "cache_speedup":
                None if uncached is None else round(
                    cached_long["tokens_per_sec"] / uncached, 2),
            "ref_cpu_tokens_per_sec": ref_cpu(),
            "vs_baseline": vs_ref(cached_long["tokens_per_sec"]),
            "note": "uncached = full fixed-length re-forward per token "
                    "on-chip (the reference's algorithm, server.py:169-181)"
                    f", bf16, marginal over tokens [{STEPS_A}, {long_steps})"
                    " for BOTH cached and uncached",
        }

    def cfg6():
        # MoE decode — second model family; the reference is dense-only
        # (SURVEY.md §2.2 "EP: not applicable"), anchor is the dense loop.
        moe_bf16 = measure_moe(PROMPT_LEN, 1, "bfloat16")
        moe_int8 = measure_moe(PROMPT_LEN, 1, "int8")
        return {
            "tokens_per_sec": round(moe_bf16["tokens_per_sec"], 2),
            "int8_tokens_per_sec": round(moe_int8["tokens_per_sec"], 2),
            "p50_token_latency_ms": round(moe_bf16["p50_token_latency_ms"], 3),
            "ref_cpu_tokens_per_sec": ref_cpu(),
            "vs_baseline": vs_ref(moe_bf16["tokens_per_sec"]),
            "note": "GPT-2 124M geometry, dense MLP -> 8 experts top-2 "
                    "(~7x MLP weights); steady-state bf16 cached decode, "
                    "plus the weight-only int8 row; reference has no MoE — "
                    "anchor is the dense 124M CPU loop",
        }

    def cfg8():
        sd = measure_spec_decode(g124, PROMPT_LEN, "bfloat16")
        row = {
            "tokens_per_sec": round(sd["spec_tokens_per_sec"], 2),
            "plain_tokens_per_sec": round(sd["plain_tokens_per_sec"], 2),
            "speedup_vs_plain": sd["speedup"],
            "accepted_tokens_per_verify": sd["accepted_tokens_per_verify"],
            "draft_len": sd["draft_len"],
            "ref_cpu_tokens_per_sec": ref_cpu(),
            "vs_baseline": vs_ref(sd["spec_tokens_per_sec"]),
            "note": "prompt-lookup speculation (runtime.spec_decode), bf16, "
                    "greedy token-exact; acceptance column shows how "
                    "repetitive this workload's greedy continuation was",
        }
        if sd.get("degraded_timing"):
            row["degraded_timing"] = True
        return row

    def cfg9():
        # llama family — RoPE + GQA (kv=4: 3x smaller KV cache) + SwiGLU.
        # The long-context column decodes at ~3k depth, past GPT-2's
        # 1024-learned-position ceiling (server.py:57).
        from llm_sharding_demo_tpu.models import llama as llama_mod
        lcfg = llama_mod.CONFIGS["llama-124m"]
        ll_bf16 = measure_engine(lcfg, PROMPT_LEN, 1, "bfloat16")
        ll_int8 = measure_engine(lcfg, PROMPT_LEN, 1, "int8")
        ll_long = measure_engine(lcfg, 3072, 1, "bfloat16")
        return {
            "tokens_per_sec": round(ll_bf16["tokens_per_sec"], 2),
            "int8_tokens_per_sec": round(ll_int8["tokens_per_sec"], 2),
            "long_context_tokens_per_sec": round(ll_long["tokens_per_sec"], 2),
            "long_context_prefill_ms": round(ll_long["prefill_ms"], 1),
            "p50_token_latency_ms": round(ll_bf16["p50_token_latency_ms"], 3),
            "ref_cpu_tokens_per_sec": ref_cpu(),
            "vs_baseline": vs_ref(ll_bf16["tokens_per_sec"]),
            "note": "llama family (RMSNorm/RoPE/SwiGLU/GQA kv=4), bf16 + "
                    "weight-only int8 steady-state decode; long-context "
                    "column = 3072-token prompt, decode at ~3-3.5k depth — "
                    "beyond the reference's 1024-position ceiling; anchor "
                    "is the dense 124M CPU loop",
        }

    def cfg7():
        return {
            "rows": measure_flash_attention(),
            "note": "Pallas K-blocked online-softmax kernel vs XLA einsum "
                    "attention, GPT-2 head geometry, bf16; fwd and fwd+bwd; "
                    "auto_dispatch = what attention_impl='pallas' actually "
                    "runs (measured-crossover dispatch, never < 1.0x XLA)",
        }

    def cfg12():
        # Megakernel batch ceiling (VERDICT r4 #6): ops.decode_layer
        # MAX_BATCH=16 silently downgrades wider batches to the
        # per-layer kernel. Pin the boundary with forced kernels:
        # bs=1 layer (the megakernel's headline win is vs this), bs=16
        # mega vs layer (is the ceiling right?), bs=32 layer (what the
        # auto fallback actually delivers past the ceiling).
        import jax as _jax
        if _jax.default_backend() == "cpu":
            return {"skipped": "megakernel crossover needs a real TPU "
                               "(CPU would measure interpret mode)"}
        rows = []
        for bs, kern in ((1, "layer"), (16, "mega"), (16, "layer"),
                         (32, "layer")):
            try:
                r = measure_engine(g124, PROMPT_LEN, bs, "bfloat16",
                                   decode_kernel=kern)
                rows.append({"batch": bs, "kernel": kern,
                             "tokens_per_sec":
                                 round(r["tokens_per_sec"], 1)})
            except Exception as e:  # noqa: BLE001 — e.g. a VMEM ceiling
                rows.append({"batch": bs, "kernel": kern,  # at bs=32
                             "error": f"{type(e).__name__}: {e}"[:200]})
        by = {(r["batch"], r["kernel"]): r.get("tokens_per_sec")
              for r in rows}  # error rows carry no rate
        mega16, layer16 = by.get((16, "mega")), by.get((16, "layer"))
        verdict = (None if not (mega16 and layer16)
                   else "mega" if mega16 >= layer16 else "layer")
        return {
            "rows": rows,
            "bs16_winner": verdict,
            "note": "auto dispatch uses mega for bs<=16 (MAX_BATCH) and "
                    "the per-layer kernel above; bs16_winner validates "
                    "the ceiling from measurement (cfg2/cfg3 carry the "
                    "auto-path bs=1/bs=8 rates to compare against the "
                    "bs=1 layer row here)",
        }

    def cfg10():
        tr = measure_training(g124)
        gp = measure_gpipe_overhead()
        return {
            **{k: v for k, v in tr.items()},
            "gpipe_cpu_mesh": gp,
            "note": "single-chip jitted train step (fwd+bwd+AdamW, remat), "
                    "GPT-2 124M bf16; MFU = 6N-per-token model FLOPs vs "
                    "the emitted peak_flops (device-kind bf16 peak; "
                    "omitted when unknown); gpipe_cpu_mesh = pp4xdp2 "
                    "pipeline schedules (GPipe M=4, 1F1B M=8) vs pure dp8 "
                    "step-time ratios on the 8-device virtual CPU mesh "
                    "(schedule overhead; CPU absolute times are not chip "
                    "numbers)",
        }

    def cfg11():
        return {
            **measure_iterbatch(g124),
            "note": "staggered arrivals (requests land mid-decode), GPT-2 "
                    "124M bf16, aggregate tokens/sec from first submit to "
                    "last completion incl. all host syncs; admission = "
                    "runtime.batcher rounds, iter = runtime.iterbatch "
                    "segment-boundary join/retire",
        }

    def cfg13():
        return {
            **measure_spec_iterbatch(g124),
            "note": "speculation x continuous batching (the previously "
                    "mutually-exclusive pair): staggered arrivals on a "
                    "REPETITIVE workload, GPT-2 124M bf16, aggregate "
                    "tokens/sec; spec_iter = draft-verify segments with "
                    "per-row acceptance (runtime.spec_decode._seg_b under "
                    "runtime.iterbatch), iter = plain single-token "
                    "segments on the same scheduler and weights; "
                    "acceptance column contextualizes the speedup (cfg8 "
                    "is the solo analog)",
        }

    def cfg14():
        return {
            **measure_paged_kv(g124),
            "note": "paged KV pool (runtime.kv_pool): solo decode "
                    "through PagedKVRunner (engine programs + one "
                    "gather/scatter per segment) vs the contiguous "
                    "engine = the paging tax; rows-before-preemption "
                    "on a 2-full-rows pool shows the concurrency "
                    "block granularity buys (contiguous arenas cap at "
                    "2 rows for the same bytes); skip-with-reason off "
                    "the bench chip",
        }

    def cfg_kv_quant_capacity():
        return {
            **measure_kv_quant_capacity(g124),
            "note": "quantized KV blocks (runtime.kv_pool block_dtype="
                    "'int8' + ops.kv_quant): rows admitted before the "
                    "first preemption and prefix-store depth, int8 vs "
                    "f32 pools at EQUAL HBM bytes (scales included) — "
                    "the effective-capacity half of the trade; the "
                    "accuracy half is the numerics_oracle row's "
                    "kv_int8_* metrics; skip-with-reason off the bench "
                    "chip",
        }

    def cfg_concurrent_load():
        return {
            **measure_concurrent_load(g124),
            "note": "width >= 4 concurrent clients through the pooled "
                    "iteration scheduler with graftsched-instrumented "
                    "locks (GRAFTSCHED=trace): p50/p99 request latency "
                    "+ per-lock wait totals — a scheduler serializing "
                    "on a blocked lock lands here before it lands in "
                    "the throughput rows; skip-with-reason off the "
                    "bench chip",
        }

    safe("cfg2_gpt2_124m_2shard_single_prompt", cfg2)
    safe("cfg3_gpt2_124m_bs8", cfg3)
    safe("cfg11_iterbatch_staggered_arrivals", cfg11)
    def cfg_fault_recovery():
        return {
            **measure_fault_recovery(g124),
            "note": "width 6 concurrent clients under a pinned 10% "
                    "transient-decode-fault seed (graftfault): p50/p99 "
                    "latency, success rate, and park/resume counts — "
                    "the price of byte-identical fault recovery rides "
                    "the gated trajectory; skip-with-reason off the "
                    "bench chip",
        }

    # graftload (ISSUE 11): ONE shared open-loop load run feeds both
    # journal rows — the Pareto sweep and the per-profile SLO
    # attainment — so the two can never disagree about what was driven
    _graftload_memo = {}

    def _graftload_result():
        if not _graftload_memo:
            try:
                _graftload_memo["result"] = measure_graftload()
            except Exception as e:  # noqa: BLE001 — both rows report it
                _graftload_memo["error"] = e
        if "error" in _graftload_memo:
            raise _graftload_memo["error"]
        return _graftload_memo["result"]

    def cfg_graftload_pareto():
        r = _graftload_result()
        if "skipped" in r:
            return {"skipped": r["skipped"]}
        return {
            "seed": r["seed"],
            "requests_per_run": r["requests_per_run"],
            "workloads": r["pareto"],
            "occupancy": r["occupancy"],
            "note": "seeded open-loop arrivals (replay-identical per "
                    "(seed, profile, k)) against the pooled iterbatch "
                    "app; one Pareto point per (profile, rate_scale) — "
                    "throughput/goodput gated higher-better, tails "
                    "lower-better by bench_diff",
        }

    def cfg_slo_attainment():
        r = _graftload_result()
        if "skipped" in r:
            return {"skipped": r["skipped"]}
        return {
            "seed": r["seed"],
            "workloads": r["slo_rows"],
            "note": "declared SLO_POLICY attainment per profile at the "
                    "base arrival rate: observed percentile vs target "
                    "per metric, goodput-under-SLO with typed 429/503 "
                    "sheds counted separately from SLO misses",
        }

    def cfg_traffic_mix():
        """The measured traffic-mix signal (ISSUE 12 satellite): one
        row per (profile, rate_scale) joining offered demand, goodput
        under the declared SLOs, and the occupancy the mix induced —
        the tuple AUTO_PLAN's continuous mode watches to decide the
        measured optimum flipped (ROADMAP item-5/6 follow-on)."""
        r = _graftload_result()
        if "skipped" in r:
            return {"skipped": r["skipped"]}
        return {
            "seed": r["seed"],
            "workloads": r["traffic_mix"],
            "note": "per-(profile, rate) demand/goodput/occupancy join "
                    "from the shared graftload run; goodput and "
                    "throughput gated higher-better, queue depth "
                    "lower-better by bench_diff",
        }

    def cfg_fleet_scaling():
        """graftfleet replica scaling (ISSUE 12): bursty_chat through
        the shared-pool fleet at 1 vs 2 decode replicas — throughput/
        goodput per replica count, router affinity hit rate, typed-shed
        split; skip-with-reason off the bench chip."""
        return measure_fleet_scaling()

    def cfg_plan_switch():
        """graftwatch live re-planning (ISSUE 13): seeded mix flip
        against the AUTO_PLAN_CONTINUOUS app — switch count, goodput/
        throughput before vs after the switch, and recompiles beyond
        the pre-certified plan set (the pinned ZERO, gated lower-better
        so a certified-envelope leak fails the trajectory); skip-with-
        reason off the bench chip."""
        return measure_plan_switch()

    def cfg_tiered_kv_depth():
        return {
            **measure_tiered_kv_depth(),
            "note": "grafttier host-RAM spill (runtime.kv_tier): a "
                    "bursty_chat-derived prefix population (loadgen "
                    "prefix_depth knob) through a small device pool + "
                    "host tier, replayed over the same seeded schedule "
                    "— ledger-measured prefix-store depth vs device "
                    "pool bytes (the >= 10x claim) plus warm-epoch "
                    "prefix/promoted hit rates and goodput (higher-"
                    "better) and promote stall (lower-better); runs on "
                    "any backend (byte accounting, not chip rates)",
        }

    safe("cfg14_paged_kv_vs_contiguous", cfg14)
    safe("kv_quant_capacity", cfg_kv_quant_capacity)
    safe("tiered_kv_depth", cfg_tiered_kv_depth)
    safe("concurrent_load", cfg_concurrent_load)
    safe("fault_recovery", cfg_fault_recovery)
    safe("graftload_pareto", cfg_graftload_pareto)
    safe("slo_attainment", cfg_slo_attainment)
    safe("traffic_mix", cfg_traffic_mix)
    safe("fleet_scaling", cfg_fleet_scaling)
    safe("plan_switch", cfg_plan_switch)
    safe("cfg4_gpt2_medium_4shard", cfg4)
    safe("cfg5_kv_cache_vs_on2", cfg5)
    safe("cfg6_moe_8e_top2_124m_geometry", cfg6)
    safe("cfg8_speculative_decode_124m", cfg8)
    safe("cfg13_spec_iterbatch_staggered", cfg13)
    safe("cfg9_llama_124m_gqa", cfg9)
    safe("cfg7_flash_attention_vs_xla", cfg7)
    safe("cfg10_training_gpt2_124m", cfg10)
    # last: the 4-engine crossover sweep is the longest single row — if
    # an external timeout cuts the run short, the classic matrix rows
    # above are already journaled
    safe("cfg12_megakernel_batch_crossover", cfg12)

    def cfg_timeline_overhead():
        """grafttime event-bus cost row (ISSUE 14): emit throughput
        into the bounded ring (events/sec) plus the bus-armed vs
        bus-off wall ratio on a tiny decode workload — min-of-3 each
        side, mirroring graftscope's pinned OVERHEAD_FACTOR pattern
        (tests/test_grafttime.py pins the bound; this row journals the
        trajectory bench_diff gates: events_per_sec higher-better,
        overhead_factor lower-better). CPU-safe, no tunnel."""
        import time as _time

        from llm_sharding_demo_tpu.fleet.harness import demo_model
        from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
        from llm_sharding_demo_tpu.utils import grafttime

        n = 20_000
        # force the bus ON for the throughput half: with GRAFTTIME=0 in
        # the environment the emits would time the disabled early
        # return and journal an inflated (and later "regressing")
        # events_per_sec
        prev = grafttime.set_enabled(True)
        try:
            t0 = _time.perf_counter()
            for i in range(n):
                grafttime.emit("occupancy", name="queue_depth",
                               value=float(i & 7))
            eps = n / (_time.perf_counter() - t0)
        finally:
            grafttime.set_enabled(prev)

        cfg_model, params = demo_model(64)
        eng = DecodeEngine(params, cfg_model, max_seq=64)
        prompt = np.full((1, 8), 5, dtype=np.int32)
        eng.generate(prompt, 16)          # warm-up: compiles

        def best_of(k: int) -> float:
            best = float("inf")
            for _ in range(k):
                t = _time.perf_counter()
                eng.generate(prompt, 16)
                best = min(best, _time.perf_counter() - t)
            return best

        prev = grafttime.set_enabled(False)
        try:
            off = best_of(3)
        finally:
            grafttime.set_enabled(prev)
        grafttime.set_enabled(True)
        try:
            on = best_of(3)
        finally:
            grafttime.set_enabled(prev)
        return {
            "events_per_sec": round(eps, 1),
            "overhead_factor": round(on / off, 4),
            "overhead_bound": grafttime.OVERHEAD_FACTOR,
            "ring_capacity": grafttime.BUS.capacity,
            "within_bound": bool(on <= off * grafttime.OVERHEAD_FACTOR),
        }

    safe("timeline_overhead", cfg_timeline_overhead)

    def cfg_hbm_attribution():
        """graftmem measured-vs-modeled byte row (ISSUE 17): the live
        ledger's per-component bytes against the cost model's aval
        arithmetic for the SAME objects — a solo f32 engine's params
        (tree_bytes over param_avals), an f32 paged pool and an int8
        paged pool (kv_pool_bytes, the allocator's own geometry math) —
        plus the ledger peak during a pooled iterbatch run. The *_drift
        fields are |measured/predicted - 1| and gate lower-better in
        bench_diff: f32 drifts are exactly 0.0 by construction (the
        tests/test_graftmem.py exactness pins, journaled), and the int8
        pool's drift below the f32-aval prediction is the quantizer's
        designed savings — CONSTANT for fixed geometry, so any movement
        means the ledger or the model changed. CPU-safe, no tunnel."""
        import sys as _sys

        import jax

        from llm_sharding_demo_tpu.fleet.harness import demo_model
        from llm_sharding_demo_tpu.runtime.engine import DecodeEngine
        from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
        from llm_sharding_demo_tpu.runtime.kv_pool import KVBlockPool
        from llm_sharding_demo_tpu.utils import graftmem

        if not graftmem.enabled():
            return {"skipped": "GRAFTMEM=0 in the environment — the "
                               "ledger registers nothing to attribute"}

        here = os.path.dirname(os.path.abspath(__file__))
        added = here not in _sys.path
        if added:
            _sys.path.insert(0, here)
        try:
            from tools.graftcheck import costmodel as _cm
        finally:
            if added:
                try:
                    _sys.path.remove(here)
                except ValueError:
                    pass
        from llm_sharding_demo_tpu.models import gpt2 as _gpt2

        cfg_model, params = demo_model(64)
        eng = DecodeEngine(params, cfg_model, max_seq=64, dtype="float32")
        f32_pool = KVBlockPool.for_engine(eng, num_blocks=16, block_size=16)
        q_pool = KVBlockPool.for_engine(eng, num_blocks=16, block_size=16,
                                        block_dtype="int8")

        # predictions from aval arithmetic only — no live buffer reads
        pred_params = _cm.tree_bytes(_cm.param_avals(_gpt2, cfg_model))
        pred_pool = _cm.kv_pool_bytes(cfg_model, 16, 16)

        def drift(measured: int, predicted: int) -> float:
            return round(abs(measured / predicted - 1.0), 6)

        m_params = graftmem.holding_bytes(eng, "params")
        m_f32 = (graftmem.holding_bytes(f32_pool, "data")
                 + graftmem.holding_bytes(f32_pool, "scales"))
        m_int8 = (graftmem.holding_bytes(q_pool, "data")
                  + graftmem.holding_bytes(q_pool, "scales"))

        # peak during a pooled iterbatch run: the working cache +
        # spec-free decode path registers/releases through the ledger
        ib = IterBatchingEngine(eng, max_batch=2, seg_steps=8,
                                max_wait_ms=10.0, pool=f32_pool)
        rng = np.random.default_rng(17)
        prompt = rng.integers(0, cfg_model.vocab_size, size=(12,))
        ib.generate(prompt, 8, timeout=120)
        snap = graftmem.snapshot()
        return {
            "params_measured_bytes": int(m_params),
            "params_predicted_bytes": int(pred_params),
            "params_drift": drift(m_params, pred_params),
            "pool_f32_measured_bytes": int(m_f32),
            "pool_f32_predicted_bytes": int(pred_pool),
            "pool_f32_drift": drift(m_f32, pred_pool),
            "pool_int8_measured_bytes": int(m_int8),
            # the int8 pool against the f32-aval prediction: the drift
            # IS the designed savings (codes narrow 4x, scales ride on
            # top) — constant for fixed geometry, gated lower-better
            "pool_int8_drift": drift(m_int8, pred_pool),
            "peak_bytes": int(snap["peak_bytes"]),
            "engine_cache_peak_bytes": int(
                snap["peaks"].get("engine_cache", {}).get("bytes", 0)),
            "ledger": {c: int(b)
                       for c, b in graftmem.component_bytes().items()},
            "conserved": bool(snap["conserved"]),
        }

    safe("hbm_attribution", cfg_hbm_attribution)

    def cfg_trend_detection():
        """grafttrend seeded detection row (ISSUE 19): the plan-switch
        traffic mix (serial -> open burst -> serial, agentic profile)
        against the AUTO_PLAN_CONTINUOUS app with a dedicated
        TrendReducer polling the live registry between phases —
        journals whether the seeded burst tripped a declared watch
        (burst_detected, gated higher-better: a reducer that stops
        seeing its pinned burst went blind) and the alerts fired
        during the QUIET serial phases (false_positives, gated
        lower-better: a watch that pages on healthy traffic is worse
        than no watch). Seed-pinned arrivals make both trajectories,
        not noise.

        Needs the bench chip: on CPU the decode dominates and the
        open burst saturates the host, so the quiet phases would trip
        latency watches on machine noise, not traffic shape.
        """
        import jax

        if jax.default_backend() != "tpu":
            return {"skipped": "trend detection needs the bench chip "
                               "(on CPU the open burst saturates the "
                               "host and the quiet phases trip "
                               "latency watches on machine noise, "
                               "not traffic shape)"}

        from llm_sharding_demo_tpu import loadgen
        from llm_sharding_demo_tpu.utils import grafttrend
        from tools.graftload import build_demo_app

        seed, n_requests = 7, 10
        prof = loadgen.profile("agentic")
        sched = loadgen.schedule(prof, seed, n_requests)
        classes = sorted({(len(a.prompt.encode("utf-8")), a.max_new)
                          for a in sched})
        traffic = ",".join(f"{p}/{n}" for p, n in classes)
        client, recorder, reg = build_demo_app(
            max_seq=256, max_batch=4,
            recorder_capacity=max(64, 8 * n_requests),
            continuous=True, auto_plan_traffic=traffic)
        red = grafttrend.TrendReducer(registry=reg, blackbox=False)

        def run_phase(mode, rate=1.0):
            rep = loadgen.run_load(client, prof, seed=seed,
                                   n=n_requests, mode=mode,
                                   rate_scale=rate, recorder=recorder,
                                   trend=red)
            return rep["trend"]["alerts_fired"]

        red.poll()                    # seed histogram/counter cursors
        quiet1 = run_phase("serial")          # quiet: stays solo
        burst_alerts = run_phase("open", rate=60.0)  # the seeded burst
        quiet2 = run_phase("serial")          # drain: quiet again
        false_pos = quiet1 + quiet2
        return {
            "seed": seed,
            "requests_per_run": n_requests,
            "watches_declared": len(grafttrend.WATCH_POLICY),
            "burst_detected": int(burst_alerts > 0),
            "burst_alerts": burst_alerts,
            "false_positives": false_pos,
            "tripped": sorted({a["watch"] for a in red.alerts()}),
        }

    safe("trend_detection", cfg_trend_detection)

    def cfg_bench_diff():
        """Perf-regression verdict (ISSUE 9, tools/bench_diff.py): THIS
        run's rows so far compared against the committed BENCH_r*.json
        trajectory with per-metric thresholds — a step-function
        regression lands in the journal as its own row instead of aging
        silently in the trajectory. Runs after every measurement row so
        the verdict covers the whole matrix."""
        import glob as _glob
        import sys as _sys
        here = os.path.dirname(os.path.abspath(__file__))
        tools = os.path.join(here, "tools")
        added = tools not in _sys.path
        if added:
            _sys.path.insert(0, tools)
        try:
            import bench_diff as _bd
        finally:
            if added:
                try:
                    _sys.path.remove(tools)
                except ValueError:
                    pass
        current = _bd.extract_metrics({"configs": configs})
        history = _bd.load_history(
            _glob.glob(os.path.join(here, "BENCH_r*.json")))
        verdict = _bd.compare(
            current, history,
            current_errors=_bd.error_configs({"configs": configs}),
            current_skips=_bd.skipped_configs({"configs": configs}))
        return {
            "ok": verdict["ok"],
            "compared": verdict["compared"],
            "regressions": verdict["regressions"],
            # skip-with-reason rows that contributed no gated metrics
            # this run — visible in the verdict instead of vanishing
            # (tools/bench_diff.py --no-skips turns these into a
            # nonzero exit for CI)
            "ungated_rows": verdict["ungated_rows"],
            # the --no-skips verdict as journaled DATA: false whenever
            # any row skipped (e.g. the TPU tunnel is down, see
            # BENCH_r05.json) — the blind spot is loud in the row
            # itself, not only behind the opt-in flag
            "no_skips_ok": verdict["no_skips_ok"],
            "history_runs": verdict["history_runs"],
            # full per-metric rows only when something regressed — the
            # OK case stays one compact journal line
            **({"rows": [r for r in verdict["rows"]
                         if r["status"] == "regression"]}
               if verdict["regressions"] else {}),
        }

    safe("bench_diff", cfg_bench_diff)

    by_name = {c["name"]: c for c in configs}
    head = by_name.get("cfg2_gpt2_124m_2shard_single_prompt", {})
    batched = by_name.get("cfg3_gpt2_124m_bs8", {})
    emit({
        "metric": "greedy_decode_throughput_gpt2_124m",
        "value": head.get("engine_bf16_tokens_per_sec"),
        "unit": "tokens/sec",
        "vs_baseline": head.get("engine_bf16_vs_baseline"),
        "dtype": "bfloat16",
        "fp32_tokens_per_sec": head.get("engine_fp32_tokens_per_sec"),
        # THE serving metric (aggregate batched decode) alongside the
        # round-1-compatible single-stream headline
        "batched_bs8_tokens_per_sec": batched.get("tokens_per_sec"),
        "transfer_rtt_ms": None if rtt_ms is None else round(rtt_ms, 1),
        "configs": configs,
    })


if __name__ == "__main__":
    main()
