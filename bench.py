"""Benchmark harness: TPU decode throughput vs the reference's CPU loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Primary metric (BASELINE.json): greedy decode tokens/sec on GPT-2 124M on
the visible TPU chip. The baseline denominator is the reference's decode
algorithm measured in-process on CPU: a torch GPT-2 that re-forwards the
FULL growing sequence per token (reference server.py:169-181 — it has no
KV cache), greedy-decoded with the same prompt/token counts. Running it
in-process (no HTTP/JSON hops, which cost the reference extra) makes the
baseline conservative — the real reference is slower than this number.

Both sides use random-init weights of the same architecture (this image
has no HF hub access; throughput is weight-independent).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def measure_reference_cpu(config, prompt_len: int, new_tokens: int) -> float:
    """tokens/sec of the reference's O(n²) CPU decode loop (torch)."""
    import torch
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    torch.manual_seed(0)
    model = GPT2LMHeadModel(HFConfig(
        vocab_size=config.vocab_size, n_positions=config.n_positions,
        n_embd=config.n_embd, n_layer=config.n_layer, n_head=config.n_head))
    model.eval()
    ids = list(np.random.default_rng(0).integers(
        0, config.vocab_size, size=(prompt_len,)))
    # warmup one forward (thread pools, allocator)
    with torch.no_grad():
        model(torch.tensor([ids]))
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        with torch.no_grad():
            logits = model(torch.tensor([ids])).logits[0, -1]
        ids.append(int(torch.argmax(logits)))  # greedy parity mode
    dt = time.perf_counter() - t0
    return new_tokens / dt


def measure_tpu(config, prompt_len: int, new_tokens: int,
                batch: int) -> dict:
    """Our engine: jitted prefill + scanned KV-cache decode on one chip.

    The bench environment exposes a single TPU chip, so this measures the
    single-device engine; the multi-stage pipeline path is validated (not
    timed) by tests on a forced-host mesh."""
    import jax

    from llm_sharding_demo_tpu.models import gpt2
    from llm_sharding_demo_tpu.runtime.engine import DecodeEngine

    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    max_seq = prompt_len + new_tokens
    engine = DecodeEngine(params, config, max_seq=max_seq)
    prompt = np.random.default_rng(0).integers(
        0, config.vocab_size, size=(batch, prompt_len))
    engine.generate(prompt, new_tokens)            # warmup: compile both programs
    result = engine.generate(prompt, new_tokens)   # measured, compile-free
    return {
        "tokens_per_sec": result.tokens_per_second,
        "p50_token_latency_ms": result.per_token_latency * 1e3,
        "prefill_ms": result.prefill_seconds * 1e3,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--prompt-len", type=int, default=16)
    parser.add_argument("--new-tokens", type=int, default=64)
    parser.add_argument("--baseline-tokens", type=int, default=20,
                        help="reference CPU loop is O(n²); 20 tokens "
                             "matches the notebook's workload")
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument("--quick", action="store_true",
                        help="tiny model for a fast smoke run")
    args = parser.parse_args()

    from llm_sharding_demo_tpu.models import gpt2

    config = gpt2.CONFIGS["tiny-gpt2" if args.quick else "gpt2"]

    ref_tps = measure_reference_cpu(config, args.prompt_len,
                                    args.baseline_tokens)
    ours = measure_tpu(config, args.prompt_len, args.new_tokens,
                       batch=args.batch)

    print(json.dumps({
        "metric": "greedy_decode_throughput_gpt2_124m"
                  if not args.quick else "greedy_decode_throughput_tiny",
        "value": round(ours["tokens_per_sec"], 2),
        "unit": "tokens/sec",
        "vs_baseline": round(ours["tokens_per_sec"] / ref_tps, 2),
        "baseline_cpu_tokens_per_sec": round(ref_tps, 2),
        "p50_token_latency_ms": round(ours["p50_token_latency_ms"], 3),
        "prefill_ms": round(ours["prefill_ms"], 2),
        "batch": args.batch,
    }))


if __name__ == "__main__":
    main()
